"""Worker that heartbeats then hangs (restart 0) or succeeds (restart>=1):
exercises the launcher's stale-heartbeat hang detection."""
import os
import sys
import time

sys.path.insert(0, os.environ["PADDLE_TPU_REPO"])
from paddle_tpu.distributed import env

env._start_heartbeat(interval=0.2)
restart = int(os.environ.get("PADDLE_RESTART_COUNT", 0))
if restart == 0 and os.environ["PADDLE_TRAINER_ID"] == "0":
    # stop beating and hang: overwrite mtime once, then sleep forever
    time.sleep(1.0)
    # kill our own heartbeat by removing the env file path's updates:
    # simplest hang = block the main thread AND stop the beat thread by
    # removing write permission on the file's directory is overkill —
    # instead exec a beatless sleep
    os.execv(sys.executable, [sys.executable, "-c", "import time; time.sleep(600)"])
print("HANG_RUNNER_OK", os.environ["PADDLE_TRAINER_ID"])
