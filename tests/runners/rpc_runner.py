"""Worker for the 2-process RPC test: rank 1 serves under a custom name,
rank 0 addresses it BY NAME (reference addressing mode)."""
import os
import sys
import time

sys.path.insert(0, os.environ["PADDLE_TPU_REPO"])

from paddle_tpu.distributed import rpc


def add(a, b):
    return a + b


def whoami():
    return int(os.environ["PADDLE_TRAINER_ID"])


rank = int(os.environ["PADDLE_TRAINER_ID"])
name = "master_worker" if rank == 0 else "side_worker"
rpc.init_rpc(name)
time.sleep(1.0)          # let both listeners come up
if rank == 0:
    # name addressing must resolve even though rank 1 chose its own name
    assert rpc.rpc_sync("side_worker", add, (2, 3)) == 5
    fut = rpc.rpc_async(1, whoami)
    assert fut.result() == 1
    assert rpc.rpc_sync(0, add, (1, 1)) == 2     # local fast path
    assert rpc.get_worker_info("side_worker").rank == 1
    print("RPC_OK")
else:
    time.sleep(4.0)      # serve until rank 0 is done
    print("RPC_OK")
rpc.shutdown()
