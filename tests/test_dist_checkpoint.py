"""Distributed checkpoint tests: shard-dedup save, reshard-on-load across
mesh changes (the reference's core feature: world-size/mesh elasticity —
SURVEY.md §5 "Checkpoint / resume")."""

import os

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.auto_parallel import (ProcessMesh, Shard,
                                                  Replicate, shard_tensor)
from paddle_tpu.distributed.checkpoint import (save_state_dict,
                                               load_state_dict, Metadata)


def _mesh(shape, names):
    return ProcessMesh(np.arange(int(np.prod(shape))).reshape(shape),
                       dim_names=list(names))


def test_save_load_roundtrip_sharded(tmp_path):
    m = _mesh((4, 2), "dp mp".split())
    x = np.arange(16 * 8, dtype=np.float32).reshape(16, 8)
    y = np.random.RandomState(0).randn(6, 10).astype(np.float32)
    sd = {
        "w": shard_tensor(x, m, [Shard(0), Shard(1)]),
        "b": shard_tensor(y, m, [Replicate(), Replicate()]),
        "scalar": jnp.asarray(3.5),
    }
    save_state_dict(sd, str(tmp_path))
    target = {
        "w": jax.ShapeDtypeStruct((16, 8), jnp.float32),
        "b": jax.ShapeDtypeStruct((6, 10), jnp.float32),
        "scalar": jax.ShapeDtypeStruct((), jnp.float32),
    }
    out = load_state_dict(target, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(out["w"]), x)
    np.testing.assert_array_equal(np.asarray(out["b"]), y)
    assert float(out["scalar"]) == 3.5


def test_reshard_on_load_mesh_change(tmp_path):
    """Save sharded [Shard(0), Shard(1)] on 4x2, load onto 2x4 with
    [Shard(1), Replicate] — the elasticity oracle."""
    m1 = _mesh((4, 2), "dp mp".split())
    x = np.random.RandomState(1).randn(16, 8).astype(np.float32)
    save_state_dict({"w": shard_tensor(x, m1, [Shard(0), Shard(1)])},
                    str(tmp_path))

    m2 = _mesh((2, 4), "a b".split())
    dst = shard_tensor(np.zeros_like(x), m2, [Replicate(), Shard(1)])
    out = load_state_dict({"w": dst}, str(tmp_path))
    np.testing.assert_array_equal(np.asarray(out["w"]), x)
    assert out["w"].sharding.spec == P(None, "b")


def test_replica_dedup_storage(tmp_path):
    """Replicated tensors are stored once, not 8x."""
    m = _mesh((8,), ["dp"])
    x = np.random.RandomState(2).randn(64, 64).astype(np.float32)
    save_state_dict({"w": shard_tensor(x, m, [Replicate()])}, str(tmp_path))
    import json
    with open(os.path.join(str(tmp_path), "metadata_p0.json")) as f:
        md = json.load(f)
    assert len(md["tensors"]["w"]["shards"]) == 1
    data = np.load(os.path.join(str(tmp_path), "data_p0.npz"))
    assert len(data.files) == 1


def test_strict_missing_key(tmp_path):
    m = _mesh((8,), ["dp"])
    save_state_dict({"w": shard_tensor(np.ones((8, 8), np.float32), m,
                                       [Shard(0)])}, str(tmp_path))
    with pytest.raises(KeyError):
        load_state_dict({"nope": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                        str(tmp_path))
    out = load_state_dict({"nope": jax.ShapeDtypeStruct((8, 8), jnp.float32)},
                          str(tmp_path), strict=False)
    assert isinstance(out["nope"], jax.ShapeDtypeStruct)


def test_shape_mismatch_raises(tmp_path):
    m = _mesh((8,), ["dp"])
    save_state_dict({"w": shard_tensor(np.ones((8, 8), np.float32), m,
                                       [Shard(0)])}, str(tmp_path))
    with pytest.raises(ValueError, match="shape mismatch"):
        load_state_dict({"w": jax.ShapeDtypeStruct((4, 8), jnp.float32)},
                        str(tmp_path))


def test_async_save(tmp_path):
    m = _mesh((8,), ["dp"])
    x = np.random.RandomState(3).randn(32, 4).astype(np.float32)
    t = save_state_dict({"w": shard_tensor(x, m, [Shard(0)])},
                        str(tmp_path), async_save=True)
    t.join()
    out = load_state_dict({"w": jax.ShapeDtypeStruct((32, 4), jnp.float32)},
                          str(tmp_path))
    np.testing.assert_array_equal(np.asarray(out["w"]), x)


def test_model_state_roundtrip_with_training(tmp_path):
    """Full engine integration: train, save sharded, reload on a new
    engine, losses continue identically."""
    import paddle_tpu.nn as nn
    import paddle_tpu.optimizer as opt
    from paddle_tpu.nn.functional_call import state

    def xent(logits, y):
        lp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(lp, y[:, None], -1))

    data = []
    rs = np.random.RandomState(5)
    for i in range(4):
        data.append((rs.randn(8, 16).astype(np.float32),
                     rs.randint(0, 10, (8,)).astype(np.int32)))

    mesh = _mesh((4, 2), "dp mp".split())

    def build():
        paddle_tpu.seed(21)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 10))
        def sf(name, sub, m):
            for pn, p in list(sub._parameters.items()):
                if p is None:
                    continue
                pl = [Replicate()] * m.ndim
                if pn == "weight" and p.shape[1] % 2 == 0:
                    pl[1] = Shard(1)
                sub._parameters[pn] = shard_tensor(p, m, pl)
        dist.shard_layer(model, mesh, sf)
        return dist.Engine(model, loss=xent,
                           optimizer=opt.SGD(learning_rate=0.1),
                           process_mesh=mesh)

    e1 = build()
    e1.fit(data, epochs=1)
    save_state_dict(e1.state_dict(), str(tmp_path))
    ref = e1.fit(data, epochs=1)

    e2 = build()
    e2._params = dict(load_state_dict(e2._params, str(tmp_path)))
    got = e2.fit(data, epochs=1)
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)


def test_metadata_without_shards_raises(tmp_path):
    # a tensor present in metadata but with zero saved shards must raise,
    # not silently load as zeros (ADVICE r1)
    from paddle_tpu.distributed.checkpoint.load_state_dict import (
        _assemble_region, _ShardReader)
    from paddle_tpu.distributed.checkpoint.metadata import TensorMeta
    tm = TensorMeta(name="w", global_shape=(4, 4), dtype="float32", shards=[])
    reader = _ShardReader(str(tmp_path))
    with pytest.raises(ValueError, match="cover"):
        _assemble_region(tm, reader, (slice(0, 4), slice(0, 4)))


def test_async_save_overlaps_training_and_matches_boundary(tmp_path):
    """Orbax-style async save (SURVEY §5, round-2 VERDICT item 6): the
    device->host snapshot happens AT the save boundary, the write runs in
    the background while further (donated-buffer) train steps mutate the
    live state, and the loaded checkpoint equals the boundary state — not
    the later one."""
    import functools
    m = _mesh((8,), ["dp"])
    w = shard_tensor(np.arange(32, dtype=np.float32).reshape(8, 4),
                     m, [Shard(0)])

    @functools.partial(jax.jit, donate_argnums=(0,))
    def step(w):
        return w * 2.0 + 1.0

    boundary = np.asarray(w)             # reference copy of the save state
    t = save_state_dict({"w": w}, str(tmp_path), async_save=True)
    assert t is not None
    # keep training while the write is (possibly) in flight; donation means
    # the old device buffer is dead — only a boundary-time host snapshot
    # can be correct
    for _ in range(5):
        w = step(w)
    from paddle_tpu.distributed.checkpoint import wait_for_pending_saves
    wait_for_pending_saves(str(tmp_path))
    got = load_state_dict({"w": jax.ShapeDtypeStruct((8, 4), jnp.float32)},
                          str(tmp_path))
    np.testing.assert_array_equal(np.asarray(got["w"]), boundary)
    # and the live state really moved on
    assert not np.allclose(np.asarray(w), boundary)


def test_async_save_rendezvous_on_next_save(tmp_path):
    """A second save to the same path joins the in-flight write first —
    successive checkpoints never interleave their files."""
    m = _mesh((8,), ["dp"])
    w1 = shard_tensor(np.ones((8, 4), np.float32), m, [Shard(0)])
    w2 = shard_tensor(np.full((8, 4), 7.0, np.float32), m, [Shard(0)])
    t1 = save_state_dict({"w": w1}, str(tmp_path), async_save=True)
    save_state_dict({"w": w2}, str(tmp_path))  # sync save: must rendezvous
    assert not t1.is_alive()                   # first write was joined
    got = load_state_dict({"w": jax.ShapeDtypeStruct((8, 4), jnp.float32)},
                          str(tmp_path))
    np.testing.assert_array_equal(np.asarray(got["w"]), 7.0)


def test_async_save_failure_surfaces_at_rendezvous(tmp_path):
    """A background write failure must raise at wait_for_pending_saves,
    not vanish into the thread (review r3: the durability guarantee)."""
    from paddle_tpu.distributed.checkpoint import wait_for_pending_saves

    target = tmp_path / "ck"
    m = _mesh((8,), ["dp"])
    w = shard_tensor(np.ones((8, 2), np.float32), m, [Shard(0)])

    t = save_state_dict({"w": w}, str(target), async_save=True)
    t.join()
    # inject the failure by replacing np.savez (numpy is shared with the
    # implementation module, so the background write hits the stub)
    real_savez = np.savez

    def boom(*a, **k):
        raise OSError("disk full (injected)")

    np.savez = boom
    try:
        save_state_dict({"w": w}, str(target), async_save=True)
        with pytest.raises(RuntimeError, match="incomplete"):
            wait_for_pending_saves(str(target))
    finally:
        np.savez = real_savez
