"""Fused decode-block megakernel (kernels/decode_block.py) — ISSUE 7.

The load-bearing contracts:
  * kernel parity: the Pallas pair (attention block + proj/MLP block)
    matches the composed-op reference numerically at fp32 AND bf16, GQA
    included, over ragged per-slot ``seq_pos`` including empty (pos=0)
    and full (pos=S) slots — and the in-kernel KV append lands exactly
    where ``append_kv`` would put it;
  * VMEM planning: ``plan_decode_block`` shrinks tiles under a budget
    and REFUSES (with a reason) when the irreducible residents cannot
    fit, which ``fusion_legal``/the engine surface as the fallback;
  * engine e2e: with ``fused_decode=True`` the engine is token-for-token
    identical to the unfused path for greedy and seeded sampling on GPT
    and Llama (GQA) f32 configs, the program set stays {chunk} + buckets
    + ONE decode, and the obs event/histogram mark the fused path.

Every kernel call here runs under ``interpret=True`` (the CPU default),
so the whole contract — including the manual DMA append and the aliased
slab update — is exercised on every tier-1 CPU run.

Named ``test_zz_*`` ON PURPOSE (same reason as test_zz_bench_projection):
this container's jaxlib-0.4 pin has the timing-dependent CPU crasher
conftest.py documents, and ``test_decode_block.py``'s natural sort
position — immediately before ``test_dist_*`` — reproducibly segfaulted
``test_dist_checkpoint`` by inserting heavy Pallas-interpret work right
before the fragile distributed window.  Sorting last keeps that window's
order byte-identical to the pre-PR suite.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.kernels.decode_block import (decode_block_layer,
                                             decode_block_reference,
                                             decode_block_route,
                                             fusion_legal,
                                             plan_decode_block)
from paddle_tpu.models import (GPTForCausalLM, LlamaForCausalLM, gpt_tiny)
from paddle_tpu.models.llama import llama_tiny
from paddle_tpu.serving import SamplingParams, ServingEngine, bucket_length


# ------------------------------------------------------ kernel-level parity

def _gpt_layer_weights(rs, d, ffn, dtype):
    A = lambda *s: jnp.asarray(rs.randn(*s), dtype) * 0.08
    return dict(norm="layer", eps1=1e-5, eps2=1e-5,
                norm1_w=A(d) + 1, norm1_b=A(d),
                wq=A(d, d), wk=A(d, d), wv=A(d, d),
                bq=A(d), bkv=A(d), bv=A(d),
                wo=A(d, d), bo=A(d),
                norm2_w=A(d) + 1, norm2_b=A(d),
                w1=A(d, ffn), b1=A(ffn), w2=A(ffn, d), b2=A(d),
                act="gelu_tanh")


def _llama_layer_weights(rs, d, h, kh, dh, ffn, dtype):
    A = lambda *s: jnp.asarray(rs.randn(*s), dtype) * 0.08
    return dict(norm="rms", eps1=1e-5, eps2=1e-5,
                norm1_w=A(d) + 1, norm1_b=None,
                wq=A(d, h * dh), wk=A(d, kh * dh), wv=A(d, kh * dh),
                bq=None, bkv=None, bv=None,
                wo=A(h * dh, d), bo=None,
                norm2_w=A(d) + 1, norm2_b=None,
                w1=A(d, ffn), b1=None, w2=A(ffn, d), b2=None,
                w_gate=A(d, ffn))


def _run_both(x, k, v, pos, kv_heads, head_dim, kw):
    y, k2, v2 = decode_block_layer(x, k, v, pos, kv_heads=kv_heads,
                                   head_dim=head_dim, **kw)
    yr, k2r, v2r = decode_block_reference(x, k, v, pos, kv_heads=kv_heads,
                                          head_dim=head_dim, **kw)
    return (y, k2, v2), (yr, k2r, v2r)


def test_parity_fp32_gpt_shape_ragged_pos():
    """LayerNorm + biases + gelu_tanh (the GPT block wiring), MHA, over
    ragged positions including an EMPTY slot (pos=0: attends only its
    ride-along token) and a FULL slot (pos=S: overwrites the last row,
    exactly dynamic_update_slice's clamp)."""
    rs = np.random.RandomState(0)
    B, S, H, Dh = 4, 64, 4, 16
    D = H * Dh
    x = jnp.asarray(rs.randn(B, 1, D), jnp.float32) * 0.1
    k = jnp.asarray(rs.randn(B, S, H, Dh), jnp.float32) * 0.1
    v = jnp.asarray(rs.randn(B, S, H, Dh), jnp.float32) * 0.1
    pos = jnp.asarray([0, 17, 63, 64], jnp.int32)   # empty..full
    kw = _gpt_layer_weights(rs, D, 4 * D, jnp.float32)
    (y, k2, v2), (yr, k2r, v2r) = _run_both(x, k, v, pos, H, Dh, kw)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(k2), np.asarray(k2r),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(v2), np.asarray(v2r),
                               rtol=2e-5, atol=2e-5)


def test_parity_bf16_gqa_rope():
    """bf16 storage, GQA (2 q heads per kv head), rotary in matrix form,
    SwiGLU — the Llama wiring.  Both sides accumulate in f32 and store
    the appended K/V in bf16, so the slabs match EXACTLY and the
    activation matches to bf16 resolution."""
    rs = np.random.RandomState(1)
    B, S, H, KH, Dh = 3, 32, 4, 2, 16
    D, F = H * Dh, 176
    dt = jnp.bfloat16
    x = jnp.asarray(rs.randn(B, 1, D), dt) * 0.1
    k = jnp.asarray(rs.randn(B, S, KH, Dh), dt) * 0.1
    v = jnp.asarray(rs.randn(B, S, KH, Dh), dt) * 0.1
    pos = jnp.asarray([0, 9, 31], jnp.int32)
    ang = rs.rand(B, Dh // 2).astype(np.float32)
    cos = jnp.concatenate([jnp.cos(ang)] * 2, axis=-1)
    sin = jnp.concatenate([jnp.sin(ang)] * 2, axis=-1)
    kw = _llama_layer_weights(rs, D, H, KH, Dh, F, dt)
    kw.update(rope_cos=cos, rope_sin=sin)
    (y, k2, v2), (yr, k2r, v2r) = _run_both(x, k, v, pos, KH, Dh, kw)
    np.testing.assert_array_equal(np.asarray(k2).view(np.uint16),
                                  np.asarray(k2r).view(np.uint16))
    np.testing.assert_array_equal(np.asarray(v2).view(np.uint16),
                                  np.asarray(v2r).view(np.uint16))
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(yr, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_parity_mixed_biases_and_scalar_pos():
    """Each bias is INDEPENDENTLY optional (bq+bo set, bkv/bv/b1/b2
    None must neither crash nor silently zero the set ones), and a
    0-d ``seq_pos`` — the single-request ``decode_step`` cache shape —
    broadcasts to the per-slot vector."""
    rs = np.random.RandomState(7)
    B, S, H, KH, Dh, F = 2, 32, 4, 2, 16, 64
    D = H * Dh
    A = lambda *s: jnp.asarray(rs.randn(*s), jnp.float32) * 0.08
    kw = dict(norm="layer", eps1=1e-5, eps2=1e-5,
              norm1_w=A(D) + 1, norm1_b=A(D),
              wq=A(D, H * Dh), wk=A(D, KH * Dh), wv=A(D, KH * Dh),
              bq=A(H * Dh), bkv=None, bv=None,
              wo=A(H * Dh, D), bo=A(D),
              norm2_w=A(D) + 1, norm2_b=A(D),
              w1=A(D, F), b1=None, w2=A(F, D), b2=A(D))
    x = A(B, 1, D)
    k = A(B, S, KH, Dh)
    v = A(B, S, KH, Dh)
    pos = jnp.asarray([3, 17], jnp.int32)
    (y, k2, v2), (yr, k2r, v2r) = _run_both(x, k, v, pos, KH, Dh, kw)
    np.testing.assert_allclose(np.asarray(y), np.asarray(yr),
                               rtol=2e-5, atol=2e-5)
    np.testing.assert_allclose(np.asarray(k2), np.asarray(k2r),
                               rtol=2e-5, atol=2e-5)
    # scalar seq_pos == uniform vector seq_pos
    ys, ks, vs = decode_block_layer(x, k, v, jnp.asarray(5, jnp.int32),
                                    kv_heads=KH, head_dim=Dh, **kw)
    yv, kvv, vv = decode_block_layer(x, k, v, jnp.full((B,), 5, jnp.int32),
                                     kv_heads=KH, head_dim=Dh, **kw)
    np.testing.assert_array_equal(np.asarray(ys), np.asarray(yv))
    np.testing.assert_array_equal(np.asarray(ks), np.asarray(kvv))


def test_kv_append_lands_at_slot_position():
    """The in-kernel DMA writes each slot's fresh K/V row at exactly
    ``min(pos, S-1)`` and touches nothing else."""
    rs = np.random.RandomState(2)
    B, S, KH, Dh = 3, 16, 2, 16
    H, D = 2, 32
    x = jnp.asarray(rs.randn(B, 1, D), jnp.float32) * 0.1
    k0 = jnp.asarray(rs.randn(B, S, KH, Dh), jnp.float32)
    v0 = jnp.asarray(rs.randn(B, S, KH, Dh), jnp.float32)
    pos = jnp.asarray([0, 5, 16], jnp.int32)
    kw = _llama_layer_weights(rs, D, H, KH, Dh, 64, jnp.float32)
    (y, k2, v2), (yr, k2r, v2r) = _run_both(x, k0, v0, pos, KH, Dh, kw)
    for b, p in enumerate([0, 5, 15]):                # 16 clamps to 15
        assert not np.allclose(np.asarray(k2)[b, p], np.asarray(k0)[b, p])
        untouched = np.delete(np.asarray(k2)[b], p, axis=0)
        np.testing.assert_array_equal(
            untouched, np.delete(np.asarray(k0)[b], p, axis=0))
    np.testing.assert_allclose(np.asarray(k2), np.asarray(k2r),
                               rtol=1e-6, atol=1e-6)


def test_block_k_tiling_matches_untiled():
    """Forcing a small streaming tile (block_k) changes the loop
    schedule, never the result."""
    rs = np.random.RandomState(3)
    B, S, KH, Dh, H = 2, 64, 2, 16, 2
    D = H * Dh
    x = jnp.asarray(rs.randn(B, 1, D), jnp.float32) * 0.1
    k = jnp.asarray(rs.randn(B, S, KH, Dh), jnp.float32) * 0.1
    v = jnp.asarray(rs.randn(B, S, KH, Dh), jnp.float32) * 0.1
    pos = jnp.asarray([33, 64], jnp.int32)
    kw = _llama_layer_weights(rs, D, H, KH, Dh, 64, jnp.float32)
    y_a, k_a, _ = decode_block_layer(x, k, v, pos, kv_heads=KH,
                                     head_dim=Dh, block_k=8, **kw)
    y_b, k_b, _ = decode_block_layer(x, k, v, pos, kv_heads=KH,
                                     head_dim=Dh, block_k=64, **kw)
    np.testing.assert_allclose(np.asarray(y_a), np.asarray(y_b),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(k_a), np.asarray(k_b))


# ------------------------------------------------- VMEM planning / legality

def test_plan_shrinks_tiles_under_budget():
    base = dict(max_seq=8192, hidden=1024, heads=8, kv_heads=8,
                head_dim=128, ffn=4096, batch=8, itemsize=2)
    roomy, why = plan_decode_block(vmem_budget=12 << 20, **base)
    tight, why2 = plan_decode_block(vmem_budget=5 << 20, **base)
    assert why is None and why2 is None
    assert tight["block_k"] < roomy["block_k"] or \
        tight["block_f"] < roomy["block_f"]
    assert tight["vmem_attn"] <= 5 << 20
    assert tight["vmem_mlp"] <= 5 << 20


def test_plan_refuses_when_residents_cannot_fit():
    plan, why = plan_decode_block(
        max_seq=8192, hidden=4096, heads=32, kv_heads=32, head_dim=128,
        ffn=16384, batch=8, itemsize=2, vmem_budget=1 << 20)
    assert plan is None and "vmem" in why
    ok, reason = fusion_legal(
        max_seq=8192, hidden=4096, heads=32, kv_heads=32, head_dim=128,
        ffn=16384, batch=8, dtype="bfloat16", vmem_budget=1 << 20)
    assert not ok and "vmem" in reason


def test_fusion_legal_shape_and_dtype_refusals():
    base = dict(max_seq=64, hidden=64, heads=4, kv_heads=2, head_dim=16,
                ffn=176, batch=2)
    ok, _ = fusion_legal(dtype="float32", gated=True, **base)
    assert ok
    ok, reason = fusion_legal(dtype="float16", **base)
    assert not ok and "float16" in reason
    ok, reason = fusion_legal(max_seq=64, hidden=64, heads=3, kv_heads=2,
                              head_dim=16, ffn=176, batch=2,
                              dtype="float32")
    assert not ok


def test_route_respects_pallas_never_flag():
    from paddle_tpu.core.flags import flags
    old = flags.pallas_routing
    try:
        flags.pallas_routing = "never"
        ok, reason = decode_block_route(64)
        assert not ok and "never" in reason
        flags.pallas_routing = "auto"
        ok, reason = decode_block_route(64)
        assert ok and reason is None
    finally:
        flags.pallas_routing = old


# --------------------------------------------------------- engine e2e parity

@pytest.fixture(scope="module")
def gpt():
    with jax.default_prng_impl("rbg"):
        return GPTForCausalLM(gpt_tiny())


@pytest.fixture(scope="module")
def llama():
    with jax.default_prng_impl("rbg"):
        return LlamaForCausalLM(llama_tiny())


def _serve(model, fused, sampled, lengths=(5, 11, 3), n_new=8):
    rs = np.random.RandomState(3)
    eng = ServingEngine(model, num_slots=3, max_seq=64, min_bucket=8,
                        fused_decode=fused)
    hs = []
    for i, L in enumerate(lengths):
        sp = SamplingParams(do_sample=True, temperature=0.9, top_k=40,
                            seed=7 + i) if sampled else None
        hs.append(eng.submit(rs.randint(0, 256, (L,)),
                             max_new_tokens=n_new, sampling=sp))
    eng.run_until_complete(max_steps=300)
    toks = {h: list(eng.result(h).tokens) for h in hs}
    return toks, eng


@pytest.mark.parametrize("sampled", [False, True],
                         ids=["greedy", "sampled"])
def test_engine_parity_gpt(gpt, sampled):
    a, ea = _serve(gpt, False, sampled)
    b, eb = _serve(gpt, True, sampled)
    assert ea.core.decode_path == "unfused"
    assert eb.core.decode_path == "fused"
    assert eb.core.decode_fallback_reason is None
    assert a == b


@pytest.mark.parametrize("sampled", [False, True],
                         ids=["greedy", "sampled"])
def test_engine_parity_llama_gqa(llama, sampled):
    a, ea = _serve(llama, False, sampled)
    b, eb = _serve(llama, True, sampled)
    assert eb.core.decode_path == "fused"
    assert a == b


def test_engine_fallback_keeps_serving(gpt):
    """A model the kernel cannot fuse (fp16) still serves: the engine
    resolves to the unfused path, records the reason, and the output
    matches the flag-off run token-for-token (it IS the same program)."""
    with jax.default_prng_impl("rbg"):
        m16 = GPTForCausalLM(gpt_tiny(dtype="float16"))
    m16.to(dtype="float16")
    a, ea = _serve(m16, False, False, lengths=(5, 9), n_new=6)
    b, eb = _serve(m16, True, False, lengths=(5, 9), n_new=6)
    assert eb.core.decode_path == "unfused"
    assert "float16" in eb.core.decode_fallback_reason
    assert a == b


# ------------------------------------------------- compile-count / telemetry

def test_compile_count_pins_one_decode_with_fused_path(gpt):
    """The fused flag must not change the program set: {chunk} + pow2
    buckets + ONE decode (the single-compiled-program discipline the
    whole engine is built around)."""
    lengths = (3, 5, 8, 9, 13, 17, 20, 31, 6, 11)
    buckets = {bucket_length(L, 8, 64) for L in lengths}
    rs = np.random.RandomState(6)
    eng = ServingEngine(gpt, num_slots=3, max_seq=64, min_bucket=8,
                        fused_decode=True)
    rids = [eng.submit(rs.randint(0, 256, (L,)),
                       max_new_tokens=3 + (i % 3))
            for i, L in enumerate(lengths)]
    eng.run_until_complete(500)
    assert all(eng.result(r).finished for r in rids)
    assert eng.core.decode_path == "fused"
    assert eng.core.trace_counts["decode"] == 1
    assert eng.core.trace_counts["prefill"] == len(buckets)


def test_obs_event_and_histogram_mark_fused_path(gpt):
    toks, eng = _serve(gpt, True, False)
    evs = eng.core.metrics.tracer.events("decode_block")
    assert len(evs) == 1
    attrs = evs[0][3]
    assert attrs["active"] is True and attrs["reason"] == ""
    assert eng.core.metrics._h_decode_block.count > 0
    # unfused engine: event says inactive, histogram stays empty
    toks2, eng2 = _serve(gpt, False, False)
    evs2 = eng2.core.metrics.tracer.events("decode_block")
    assert len(evs2) == 1 and evs2[0][3]["active"] is False
    assert eng2.core.metrics._h_decode_block.count == 0


def test_bench_compare_row_smoke():
    """The fused-vs-unfused kernel_compare row bench emits on every CPU
    run: parity holds and the interpret-mode caveat note is attached."""
    import bench
    row = bench._decode_block_compare(smoke=True)
    assert row["ok"] and row["fusion_legal"]
    assert row["max_abs_diff"] < 5e-2
    assert "interpret" in row.get("note", "")


def test_bench_decode_path_info(gpt):
    import bench
    info = bench.decode_path_info(gpt, batch=4, kv_len=64)
    assert info["path"] == "unfused"
    assert info["fused_available"] is True
    info16 = bench.decode_path_info(object(), batch=4, kv_len=64)
    assert info16["fused_available"] is False
    assert "fused_decode_step" in info16["fused_fallback_reason"]
