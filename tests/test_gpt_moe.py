"""GPT-MoE model family (BASELINE config #5: expert-parallel MoE).
Oracles follow the reference pattern: EP-parallel == serial loss, aux loss
flows, training learns."""

import numpy as np

from conftest import requires_modern_jax
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
from paddle_tpu.models import GPTMoEForCausalLM, gpt_moe_tiny
from paddle_tpu.nn.functional_call import functional_call, state


def _data(batch=4, seq=16, seed=0):
    rs = np.random.RandomState(seed)
    ids = rs.randint(0, 256, (batch, seq + 1))
    return jnp.asarray(ids[:, :-1]), jnp.asarray(ids[:, 1:])


def test_gpt_moe_forward_and_aux_loss():
    paddle_tpu.seed(0)
    cfg = gpt_moe_tiny(gate="gshard")
    model = GPTMoEForCausalLM(cfg)
    model.train()
    params, buffers = state(model)
    x, y = _data()
    key = jax.random.PRNGKey(0)

    @jax.jit
    def fwd(p, b):
        out, nb = functional_call(model, p, b, (x,), rng=key, train=True)
        aux = sum(v for k, v in nb.items() if k.endswith("aux_loss"))
        return out, aux

    logits, aux = fwd(params, buffers)
    assert logits.shape == (4, 16, 256)
    assert float(aux) > 0.0          # gshard aux loss engaged


def test_gpt_moe_trains():
    paddle_tpu.seed(1)
    cfg = gpt_moe_tiny(gate="naive")   # deterministic routing
    model = GPTMoEForCausalLM(cfg)
    model.train()
    params, buffers = state(model)
    o = opt.AdamW(learning_rate=3e-3)
    ostate = o.init(params)
    x, y = _data(seed=2)

    @jax.jit
    def step(p, os_, b):
        def loss_fn(p):
            out, nb = functional_call(model, p, b, (x,), train=True)
            logp = jax.nn.log_softmax(out.astype(jnp.float32), -1)
            tok = jnp.take_along_axis(logp, y[..., None], -1)[..., 0]
            aux = sum(v for k, v in nb.items() if k.endswith("aux_loss"))
            return -jnp.mean(tok) + cfg.aux_weight * aux
        loss, g = jax.value_and_grad(loss_fn)(p)
        newp, nos = o.update(g, os_, p)
        return newp, nos, loss

    losses = []
    for _ in range(15):
        params, ostate, loss = step(params, ostate, buffers)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.9, (losses[0], losses[-1])


def test_gpt_moe_expert_parallel_matches_serial():
    """Same seed, EP over 4 devices == serial (the reference's EP oracle
    pattern at the model level)."""
    paddle_tpu.seed(7)
    cfg_s = gpt_moe_tiny(gate="naive")
    serial = GPTMoEForCausalLM(cfg_s)
    serial.eval()
    x, y = _data(seed=3)
    ps, bs = state(serial)
    out_s, _ = functional_call(serial, ps, bs, (x,), train=False)

    g = dist.collective.new_group(list(range(4)))
    paddle_tpu.seed(7)
    cfg_p = gpt_moe_tiny(gate="naive")
    cfg_p.moe_group = g
    par = GPTMoEForCausalLM(cfg_p)
    par.eval()
    pp, bp = state(par)
    out_p, _ = functional_call(par, pp, bp, (x,), train=False)
    np.testing.assert_allclose(np.asarray(out_s), np.asarray(out_p),
                               rtol=2e-4, atol=2e-4)


def test_gpt_moe_loss_single_forward_with_aux():
    """model.loss = lm + aux from ONE forward: the gates' aux buffers are
    read right after self() inside the same bind (code-review r2: the old
    signature forced a second forward or stale aux)."""
    paddle_tpu.seed(5)
    cfg = gpt_moe_tiny(gate="gshard")
    model = GPTMoEForCausalLM(cfg)
    model.train()
    params, buffers = state(model)
    x, y = _data(seed=6)
    key = jax.random.PRNGKey(1)

    from paddle_tpu.nn.functional_call import bind_state
    from paddle_tpu.framework.random import rng_context

    @jax.jit
    def run(p, b):
        with bind_state(model, p, b):
            with rng_context(key):
                return model.loss(x, y)

    total = float(run(params, buffers))
    # oracle: the two-output route with the SAME rng -> lm + w*aux
    @jax.jit
    def parts(p, b):
        out, nb = functional_call(model, p, b, (x,), rng=key, train=True)
        return GPTMoEForCausalLM.loss_from_logits(out, y, nb,
                                                  cfg.aux_weight)

    np.testing.assert_allclose(total, float(parts(params, buffers)),
                               rtol=1e-5)


def _mk_moe_trainer(hybrid, gate="naive", microbatches=1, seed=11,
                    zero=1, gate_kwargs=None):
    from paddle_tpu.models import GPTMoEHybridTrainer
    s = dist.DistributedStrategy()
    s.hybrid_configs = hybrid
    dist.fleet.init(is_collective=True, strategy=s)
    hcg = dist.get_hybrid_communicate_group()
    paddle_tpu.seed(seed)
    cfg = gpt_moe_tiny(gate=gate, moe_every=1, gate_kwargs=gate_kwargs)
    tr = GPTMoEHybridTrainer(cfg, hcg, opt.SGD(learning_rate=0.1),
                             microbatches=microbatches, zero_stage=zero)
    return tr


def _teardown_hcg():
    dist.topology.set_hybrid_communicate_group(None)


@requires_modern_jax
def test_moe_hybrid_ep_pp_zero1_matches_serial():
    """EP x pp x ZeRO-1 GPT-MoE == serial (round-2 VERDICT item 5: the
    expert axis composed with the rest of the fleet topology).

    microbatches=1 so the expert capacity (a function of the routed token
    count) sees the same token set on both paths — with M>1 the
    per-microbatch capacity legitimately differs from whole-batch serial
    (the estimator is nonlinear in the token set; GPT dense covers M>1
    schedule parity)."""
    tr1 = _mk_moe_trainer({"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                           "sharding_degree": 1, "ep_degree": 1},
                          microbatches=1)
    st1 = tr1.init_state()
    x, y = tr1.make_batch(batch=4, seq=16, seed=5)
    st1, loss1 = tr1.train_step(st1, x, y)
    st1, loss1b = tr1.train_step(st1, x, y)
    _teardown_hcg()

    tr2 = _mk_moe_trainer({"dp_degree": 1, "mp_degree": 1, "pp_degree": 2,
                           "sharding_degree": 2, "ep_degree": 2},
                          microbatches=1, zero=1)
    # experts must ride the first-class ep axis
    assert tr2.hcg.get_expert_parallel_world_size() == 2
    st2 = tr2.init_state()
    x2, y2 = tr2.make_batch(batch=4, seq=16, seed=5)
    st2, loss2 = tr2.train_step(st2, x2, y2)
    st2, loss2b = tr2.train_step(st2, x2, y2)
    _teardown_hcg()

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=2e-4)
    np.testing.assert_allclose(float(loss1b), float(loss2b), rtol=2e-3)


def test_moe_hybrid_expert_params_shard_over_ep():
    """Per-device expert bytes shrink by the ep degree: the stacked expert
    leaves carry P('pp', 'ep', ...) so no device holds the full expert
    bank (the memory point of expert parallelism)."""
    tr = _mk_moe_trainer({"dp_degree": 1, "mp_degree": 1, "pp_degree": 2,
                          "sharding_degree": 1, "ep_degree": 4},
                         microbatches=1)
    _, pblk, _, _ = tr.init_state()
    key = next(k for k in pblk if "stacked__" in k)
    arr = pblk[key]
    total = arr.size * arr.dtype.itemsize
    shard = arr.addressable_shards[0].data
    per_dev = shard.size * shard.dtype.itemsize
    # blocks over pp(2) x experts over ep(4) -> each device holds 1/8
    assert per_dev * 8 == total, (key, per_dev, total)
    _teardown_hcg()


@requires_modern_jax
def test_moe_hybrid_aux_loss_rides_pipeline():
    """Deterministic gshard (random_routing=False): the nonzero balance
    aux accumulated across pipeline stages matches the serial value at
    M=1 (exact: same token set)."""
    tr1 = _mk_moe_trainer({"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                           "sharding_degree": 1, "ep_degree": 1},
                          gate="gshard", microbatches=1, seed=13,
                          gate_kwargs={"random_routing": False})
    st1 = tr1.init_state()
    x, y = tr1.make_batch(batch=2, seq=16, seed=9)
    st1, loss1 = tr1.train_step(st1, x, y)
    # aux engaged: loss with aux_weight=0 would differ
    _teardown_hcg()

    tr2 = _mk_moe_trainer({"dp_degree": 1, "mp_degree": 1, "pp_degree": 2,
                           "sharding_degree": 1, "ep_degree": 2},
                          gate="gshard", microbatches=1, seed=13,
                          gate_kwargs={"random_routing": False})
    st2 = tr2.init_state()
    x2, y2 = tr2.make_batch(batch=2, seq=16, seed=9)
    st2, loss2 = tr2.train_step(st2, x2, y2)
    _teardown_hcg()

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=2e-4)


def test_moe_trainer_requires_uniform_blocks():
    from paddle_tpu.models import GPTMoEHybridTrainer
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 1, "pp_degree": 2, "ep_degree": 2}
    dist.fleet.init(is_collective=True, strategy=s)
    hcg = dist.get_hybrid_communicate_group()
    cfg = gpt_moe_tiny(gate="naive", moe_every=2)
    try:
        import pytest
        with pytest.raises(ValueError, match="moe_every"):
            GPTMoEHybridTrainer(cfg, hcg, opt.SGD(learning_rate=0.1))
    finally:
        _teardown_hcg()

def test_ep_mp_parity():
    """ep x mp in ONE mesh (round-3 VERDICT item 5): experts shard over ep
    with weights additionally split over mp (expert-internal tensor
    parallelism — reference: MoELayer(mp_group) alongside the moe group);
    dp x ep x mp == serial loss over two steps."""
    tr1 = _mk_moe_trainer({"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                           "sharding_degree": 1, "ep_degree": 1},
                          microbatches=1)
    st1 = tr1.init_state()
    x, y = tr1.make_batch(batch=4, seq=16, seed=21)
    st1, loss1 = tr1.train_step(st1, x, y)
    st1, loss1b = tr1.train_step(st1, x, y)
    _teardown_hcg()

    tr2 = _mk_moe_trainer({"dp_degree": 2, "mp_degree": 2, "pp_degree": 1,
                           "sharding_degree": 1, "ep_degree": 2},
                          microbatches=1)
    assert tr2.cfg.mp_group == "mp"      # trainer wired the mp group in
    st2 = tr2.init_state()
    x2, y2 = tr2.make_batch(batch=4, seq=16, seed=21)
    st2, loss2 = tr2.train_step(st2, x2, y2)
    st2, loss2b = tr2.train_step(st2, x2, y2)
    _teardown_hcg()

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=2e-4)
    np.testing.assert_allclose(float(loss1b), float(loss2b), rtol=2e-3)


def test_ep_mp_expert_params_shard_over_both_axes():
    """Stacked expert weight bytes per device shrink by ep x mp: the
    stacked w0 leaf carries P('ep', None, 'mp') — no device holds a full
    expert bank NOR a full expert's weight."""
    tr = _mk_moe_trainer({"dp_degree": 1, "mp_degree": 2, "pp_degree": 1,
                          "sharding_degree": 1, "ep_degree": 4},
                         microbatches=1)
    _, pblk, _, _ = tr.init_state()
    key = next(k for k in pblk if k.endswith("stacked__w0"))
    arr = pblk[key]
    total = arr.size * arr.dtype.itemsize
    shard = arr.addressable_shards[0].data
    per_dev = shard.size * shard.dtype.itemsize
    # experts over ep(4) x inner columns over mp(2) -> each device holds 1/8
    assert per_dev * 8 == total, (key, per_dev, total)
    _teardown_hcg()


def test_expert_stack_inherits_template_specs():
    """ExpertStack prepends the ep axis to each expert param's OWN spec —
    the composition seam that makes any internally-sharded expert
    (not just ExpertFFN) ride ep x mp."""
    from paddle_tpu.distributed.moe import ExpertFFN, ExpertStack
    from paddle_tpu.distributed.sharding_utils import get_param_specs
    paddle_tpu.seed(0)
    experts = [ExpertFFN(8, 16, mp_group="mp") for _ in range(2)]
    stack = ExpertStack(experts, moe_group="ep")
    specs = get_param_specs(stack)
    assert tuple(specs["stacked__w0"]) == ("ep", None, "mp")
    assert tuple(specs["stacked__w1"]) == ("ep", "mp", None)
    assert tuple(specs["stacked__b0"]) == ("ep", "mp")
    assert tuple(specs["stacked__b1"]) == ("ep", None)
