"""incubate.nn fused layers/functional tests.

Oracle (reference pattern: test/legacy_test/test_fused_attention_op.py and
friends): every fused op must equal its unfused composition built from the
base ops.
"""

import numpy as np
import jax
import jax.numpy as jnp

import paddle_tpu
import paddle_tpu.nn.functional as F
from paddle_tpu.incubate.nn import (FusedMultiHeadAttention, FusedFeedForward,
                                    FusedMultiTransformer)
from paddle_tpu.incubate.nn import functional as IF


def rand(*shape, seed=0):
    return jnp.asarray(np.random.RandomState(seed).randn(*shape)
                       .astype(np.float32) * 0.1)


def test_fused_linear_matches_linear():
    x, w, b = rand(4, 8), rand(8, 16, seed=1), rand(16, seed=2)
    np.testing.assert_allclose(np.asarray(IF.fused_linear(x, w, b)),
                               np.asarray(F.linear(x, w, b)), rtol=1e-6)


def test_fused_bias_dropout_residual_ln():
    x, res = rand(2, 4, 8), rand(2, 4, 8, seed=1)
    scale, bias = jnp.ones((8,)), jnp.zeros((8,))
    out = IF.fused_bias_dropout_residual_layer_norm(
        x, res, None, scale, bias, dropout_rate=0.0, training=False)
    ref = F.layer_norm(x + res, (8,), scale, bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_fused_feedforward_matches_composition():
    x = rand(2, 5, 8)
    w1, b1 = rand(8, 32, seed=1), rand(32, seed=2)
    w2, b2 = rand(32, 8, seed=3), rand(8, seed=4)
    s1, bb1 = jnp.ones((8,)), jnp.zeros((8,))
    out = IF.fused_feedforward(x, w1, w2, b1, b2, ln1_scale=s1, ln1_bias=bb1,
                               dropout1_rate=0.0, dropout2_rate=0.0,
                               activation="gelu", pre_layer_norm=True,
                               training=False)
    h = F.layer_norm(x, (8,), s1, bb1)
    ref = x + F.linear(F.gelu(F.linear(h, w1, b1)), w2, b2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-6)


def test_fused_mha_layer_runs_and_matches_functional():
    paddle_tpu.seed(0)
    layer = FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                    attn_dropout_rate=0.0,
                                    normalize_before=True)
    layer.eval()
    x = rand(2, 6, 16)
    out = layer(x)
    ref = IF.fused_multi_head_attention(
        x, layer.qkv_weight, layer.linear_weight, pre_layer_norm=True,
        pre_ln_scale=layer.pre_ln_scale, pre_ln_bias=layer.pre_ln_bias,
        qkv_bias=layer.qkv_bias, linear_bias=layer.linear_bias,
        dropout_rate=0.0, attn_dropout_rate=0.0, training=False)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-6)
    assert out.shape == x.shape


def test_fused_ffn_layer():
    paddle_tpu.seed(0)
    layer = FusedFeedForward(8, 32, dropout_rate=0.0, activation="gelu",
                             normalize_before=True)
    layer.eval()
    x = rand(2, 5, 8)
    out = layer(x)
    h = F.layer_norm(x, (8,), layer.ln1_scale, layer.ln1_bias)
    ref = x + F.linear(F.gelu(F.linear(h, layer.linear1_weight,
                                       layer.linear1_bias)),
                       layer.linear2_weight, layer.linear2_bias)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5,
                               atol=1e-6)


def test_fused_multi_transformer_prefill_decode_consistency():
    """Decode one token at a time must equal full-sequence prefill — the
    KV-cache correctness oracle for the fused_multi_transformer analog."""
    paddle_tpu.seed(0)
    B, S, M, H, L = 2, 6, 16, 4, 2
    model = FusedMultiTransformer(M, H, 32, dropout_rate=0.0, num_layers=L)
    model.eval()
    x = rand(B, S, M)

    full = model(x)                      # [B,S,M] causal self-attn

    caches = model.init_cache(B, max_seq=S)
    outs = []
    for t in range(S):
        step = x[:, t:t + 1]
        out, caches = model(step, caches=caches, time_step=t)
        outs.append(out)
    stepwise = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(stepwise), np.asarray(full),
                               rtol=5e-4, atol=5e-5)


def test_fused_rope_rotates_pairwise_norm_preserving():
    q = rand(2, 8, 4, 16)
    qr, kr, vr = IF.fused_rotary_position_embedding(q, q, None)
    assert vr is None
    # rotation preserves per-pair norms
    def pair_norm(x):
        x1, x2 = x[..., :8], x[..., 8:]
        return np.asarray(jnp.sqrt(x1 ** 2 + x2 ** 2))
    np.testing.assert_allclose(pair_norm(qr), pair_norm(q), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(qr), np.asarray(kr))
    # position 0 is unrotated
    np.testing.assert_allclose(np.asarray(qr[:, 0]), np.asarray(q[:, 0]),
                               rtol=1e-6)


def test_fused_rms_norm():
    x = rand(3, 8)
    w = jnp.ones((8,)) * 2.0
    out = IF.fused_rms_norm(x, w)
    ref = x / np.sqrt(np.mean(np.asarray(x) ** 2, -1, keepdims=True) + 1e-6) * 2.0
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5)


def test_swiglu():
    x, y = rand(4, 8), rand(4, 8, seed=1)
    np.testing.assert_allclose(np.asarray(IF.swiglu(x, y)),
                               np.asarray(jax.nn.silu(x) * y), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(IF.swiglu(jnp.concatenate([x, y], -1))),
                               np.asarray(jax.nn.silu(x) * y), rtol=1e-6)


def test_fused_mha_cache_decode_matches_full():
    paddle_tpu.seed(3)
    layer = FusedMultiHeadAttention(16, 4, dropout_rate=0.0,
                                    attn_dropout_rate=0.0,
                                    normalize_before=True)
    layer.eval()
    B, S = 2, 5
    x = rand(B, S, 16, seed=9)
    # full causal pass, step-by-step via growing cache must match
    full = []
    for t in range(S):
        # causal attention: row t attends to 0..t
        sub = layer(x[:, :t + 1],
                    attn_mask=jnp.where(
                        jnp.tril(jnp.ones((t + 1, t + 1)))[None, None] > 0,
                        0.0, -1e9))
        full.append(sub[:, -1:])
    full = jnp.concatenate(full, axis=1)

    cache = jnp.zeros((2, B, 4, 0, 4))
    outs = []
    for t in range(S):
        out, cache = layer(x[:, t:t + 1], cache=cache)
        outs.append(out)
    stepwise = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(stepwise), np.asarray(full),
                               rtol=5e-4, atol=5e-5)


def test_number_count_ignores_pruned():
    from paddle_tpu.distributed.moe import number_count
    out = np.asarray(number_count(np.array([-1, 0, 1, 1]), 3))
    np.testing.assert_array_equal(out, [1, 2, 0])


def test_fused_matmul_bias_batched_transpose():
    x = rand(2, 5, 3)
    y = rand(2, 5, 4, seed=1)
    out = IF.fused_matmul_bias(x, y, transpose_x=True)
    ref = jnp.einsum("bsi,bsj->bij", x, y)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-5)


def test_fused_mt_seq_lens_keeps_causality():
    """seq_lens padding must ADD to causality, not replace it (regression:
    passing any mask at prefill used to disable the causal mask)."""
    paddle_tpu.seed(1)
    m = FusedMultiTransformer(16, 4, 32, dropout_rate=0.0, num_layers=1)
    m.eval()
    x = rand(2, 6, 16, seed=5)
    causal_only = m(x)
    with_lens = m(x, seq_lens=jnp.asarray([6, 6]))  # no actual padding
    np.testing.assert_allclose(np.asarray(with_lens),
                               np.asarray(causal_only), rtol=1e-5, atol=1e-6)


def test_fused_rms_norm_dtype_consistent_across_routes():
    # bf16 x with f32 weight must return bf16 on BOTH the Pallas route
    # (hidden % 128 == 0) and the XLA fallback (ADVICE r1)
    rs = np.random.RandomState(3)
    w128 = jnp.ones(128, jnp.float32)
    w96 = jnp.ones(96, jnp.float32)
    x128 = jnp.asarray(rs.randn(2, 4, 128), jnp.bfloat16)
    x96 = jnp.asarray(rs.randn(2, 4, 96), jnp.bfloat16)
    assert IF.fused_rms_norm(x128, w128).dtype == jnp.bfloat16
    assert IF.fused_rms_norm(x96, w96).dtype == jnp.bfloat16


def test_fused_mt_noop_padding_mask_matches_no_mask_chunked_decode():
    """Chunked decode (sq>1 at time_step t): a semantically-empty padding
    mask must not change attention vs attn_mask=None (code-review r2: the
    dense fallback used whole-chunk length masking while the kernel path
    was causal within the chunk)."""
    paddle_tpu.seed(17)
    m = FusedMultiTransformer(embed_dim=32, num_heads=4, dim_feedforward=64,
                              num_layers=1)
    m.eval()
    rs = np.random.RandomState(3)
    B, sq, t, Tmax = 2, 4, 6, 32
    caches = m.init_cache(B, Tmax)
    # prefill t tokens first so the cache is warm
    warm = jnp.asarray(rs.randn(B, t, 32), jnp.float32)
    _, caches = m(warm, caches=caches, time_step=None)
    x = jnp.asarray(rs.randn(B, sq, 32), jnp.float32)
    out_none, _ = m(x, caches=caches, time_step=t)
    zero_mask = jnp.zeros((B, 1, 1, Tmax), jnp.float32)
    out_zero, _ = m(x, caches=caches, time_step=t, attn_mask=zero_mask)
    np.testing.assert_allclose(np.asarray(out_none), np.asarray(out_zero),
                               rtol=2e-5, atol=2e-5)


def test_fused_dropout_add_and_linear_activation():
    rs = np.random.RandomState(21)
    x = jnp.asarray(rs.randn(3, 8), jnp.float32)
    y = jnp.asarray(rs.randn(3, 8), jnp.float32)
    np.testing.assert_allclose(
        np.asarray(IF.fused_dropout_add(x, y, p=0.5, training=False)),
        np.asarray(x + y), rtol=1e-6)
    w = jnp.asarray(rs.randn(8, 4), jnp.float32)
    b = jnp.asarray(rs.randn(4), jnp.float32)
    out = IF.fused_linear_activation(x, w, b, activation="relu")
    np.testing.assert_allclose(np.asarray(out),
                               np.maximum(np.asarray(x) @ np.asarray(w)
                                          + np.asarray(b), 0), rtol=1e-5)


def test_masked_multihead_attention_matches_decode_ref():
    paddle_tpu.seed(23)
    rs = np.random.RandomState(23)
    B, H, D, T = 2, 2, 64, 16
    lens = np.array([3, 7], np.int32)
    cache = rs.randn(2, B, H, T, D).astype(np.float32) * 0.5
    # zero out invalid cache positions for clarity
    x = rs.randn(B, 3 * H * D).astype(np.float32) * 0.5
    out, new_cache = IF.masked_multihead_attention(
        jnp.asarray(x), jnp.asarray(cache),
        sequence_lengths=jnp.asarray(lens))
    assert out.shape == (B, H * D)
    # the new k/v must be written at position lens[b]
    qkv = x.reshape(B, 3, H, D)
    for b in range(B):
        np.testing.assert_allclose(
            np.asarray(new_cache[0, b, :, lens[b]]), qkv[b, 1], rtol=1e-6)
    # numpy attention oracle over the first lens[b]+1 positions
    for b in range(B):
        L = lens[b] + 1
        kc = np.asarray(new_cache[0, b])    # [H, T, D]
        vc = np.asarray(new_cache[1, b])
        q = qkv[b, 0]
        for h in range(H):
            s = (q[h] @ kc[h, :L].T) / np.sqrt(D)
            p = np.exp(s - s.max())
            p /= p.sum()
            ref = p @ vc[h, :L]
            np.testing.assert_allclose(
                np.asarray(out[b]).reshape(H, D)[h], ref, rtol=2e-4,
                atol=2e-4)


def test_fused_multi_transformer_functional_matches_layer():
    paddle_tpu.seed(24)
    m = FusedMultiTransformer(embed_dim=32, num_heads=4, dim_feedforward=64,
                              num_layers=2)
    m.eval()
    rs = np.random.RandomState(24)
    x = jnp.asarray(rs.randn(2, 6, 32), jnp.float32)
    ref = m(x)
    p = m._parameters
    L = 2
    out = IF.fused_multi_transformer(
        x,
        [p[f"ln_scale_{i}"] for i in range(L)],
        [p[f"ln_bias_{i}"] for i in range(L)],
        [p[f"qkv_weight_{i}"] for i in range(L)],
        [p[f"qkv_bias_{i}"] for i in range(L)],
        [p[f"linear_weight_{i}"] for i in range(L)],
        [p[f"linear_bias_{i}"] for i in range(L)],
        [p[f"ffn_ln_scale_{i}"] for i in range(L)],
        [p[f"ffn_ln_bias_{i}"] for i in range(L)],
        [p[f"ffn1_weight_{i}"] for i in range(L)],
        [p[f"ffn1_bias_{i}"] for i in range(L)],
        [p[f"ffn2_weight_{i}"] for i in range(L)],
        [p[f"ffn2_bias_{i}"] for i in range(L)])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
