"""paddle.utils + paddle.version parity."""

import warnings

import pytest

import paddle_tpu
from paddle_tpu.utils import run_check, deprecated, try_import, unique_name


def test_run_check_prints_success(capsys):
    assert run_check() is True
    out = capsys.readouterr().out
    assert "installed successfully" in out


def test_version_surface():
    assert paddle_tpu.__version__ == paddle_tpu.version.full_version
    paddle_tpu.version.show()


def test_deprecated_warns_and_raises():
    @deprecated(update_to="new_fn", since="0.2")
    def old_fn():
        return 42

    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        assert old_fn() == 42
        assert any("deprecated" in str(x.message) for x in w)

    @deprecated(level=2)
    def dead_fn():
        return 0

    with pytest.raises(RuntimeError, match="deprecated"):
        dead_fn()


def test_try_import_and_unique_name():
    assert try_import("math").sqrt(4) == 2
    with pytest.raises(ImportError, match="not_a_module"):
        try_import("not_a_module_xyz", "not_a_module_xyz missing")
    a = unique_name.generate("fc")
    b = unique_name.generate("fc")
    assert a != b and a.startswith("fc_")
    with unique_name.guard():
        c = unique_name.generate("fc")
        assert c == "fc_0"
