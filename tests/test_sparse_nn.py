"""paddle.sparse.nn tests — sparse 3D conv stack vs dense oracles.

Reference: python/paddle/sparse/nn (Conv3D/SubmConv3D/BatchNorm/
MaxPool3D); test model: the reference's sparse-conv unit tests compare
against dense convolution on the densified input (test/legacy_test
sparse conv tests).  Here: every op is checked against the dense
F.conv3d / max_pool3d / batch-norm computation restricted to the active
set.
"""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.experimental import sparse as jsparse

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F
from paddle_tpu.sparse import nn as snn


def _random_sparse(rng, N=2, D=6, H=6, W=6, C=4, nnz=30):
    dense = np.zeros((N, D, H, W, C), np.float32)
    pts = rng.choice(N * D * H * W, nnz, replace=False)
    for p in pts:
        n, r = divmod(int(p), D * H * W)
        d, r = divmod(r, H * W)
        h, w = divmod(r, W)
        dense[n, d, h, w] = rng.normal(size=C)
    return dense, jsparse.BCOO.fromdense(jnp.asarray(dense), n_dense=1)


def _dense_conv_ref(dense, weight, bias, stride=1, padding=0, dilation=1):
    """NDHWC dense conv3d via the dense functional path (NCDHW)."""
    w = jnp.transpose(weight, (4, 3, 0, 1, 2))
    xd = jnp.transpose(jnp.asarray(dense), (0, 4, 1, 2, 3))
    ref = F.conv3d(xd, w, bias, stride=stride, padding=padding,
                   dilation=dilation)
    return jnp.transpose(ref, (0, 2, 3, 4, 1))


class TestSubmConv3D:
    def test_matches_dense_conv_on_active_set(self):
        rng = np.random.default_rng(0)
        dense, x = _random_sparse(rng)
        paddle.seed(0)
        conv = snn.SubmConv3D(4, 8, 3)
        y = conv(x)
        assert y.shape == (2, 6, 6, 6, 8)
        ref = _dense_conv_ref(dense, conv.weight, conv.bias, padding=1)
        mask = (np.abs(dense).sum(-1, keepdims=True) > 0)
        np.testing.assert_allclose(np.asarray(ref) * mask,
                                   np.asarray(y.todense()),
                                   rtol=1e-4, atol=1e-4)

    def test_active_set_preserved(self):
        rng = np.random.default_rng(1)
        dense, x = _random_sparse(rng, nnz=12)
        conv = snn.SubmConv3D(4, 4, 3, bias_attr=False)
        y = conv(x)
        np.testing.assert_array_equal(np.asarray(y.indices),
                                      np.asarray(x.indices))

    def test_jit_and_grad(self):
        rng = np.random.default_rng(2)
        dense, x = _random_sparse(rng, nnz=10)
        paddle.seed(1)
        conv = snn.SubmConv3D(4, 4, 3)

        @jax.jit
        def loss(w, b):
            y = snn.functional.subm_conv3d(x, w, b, padding=0)
            return (y.data ** 2).sum()

        g = jax.grad(loss, argnums=(0, 1))(conv.weight, conv.bias)
        assert np.isfinite(np.asarray(g[0])).all()
        assert float(jnp.abs(g[0]).sum()) > 0

    def test_stride_rejected(self):
        rng = np.random.default_rng(3)
        _, x = _random_sparse(rng)
        conv = snn.SubmConv3D(4, 4, 3, stride=2)
        with pytest.raises(ValueError, match="stride 1"):
            conv(x)

    def test_dilation(self):
        rng = np.random.default_rng(4)
        dense, x = _random_sparse(rng, D=8, H=8, W=8, nnz=25)
        paddle.seed(2)
        conv = snn.SubmConv3D(4, 6, 3, dilation=2)
        y = conv(x)
        ref = _dense_conv_ref(dense, conv.weight, conv.bias, padding=2,
                              dilation=2)
        mask = (np.abs(dense).sum(-1, keepdims=True) > 0)
        np.testing.assert_allclose(np.asarray(ref) * mask,
                                   np.asarray(y.todense()),
                                   rtol=1e-4, atol=1e-4)


class TestConv3D:
    @pytest.mark.parametrize("stride,padding", [(1, 0), (2, 0), (2, 1),
                                                (1, 1)])
    def test_matches_dense_conv_at_active_outputs(self, stride, padding):
        rng = np.random.default_rng(5)
        dense, x = _random_sparse(rng, nnz=20)
        paddle.seed(3)
        conv = snn.Conv3D(4, 5, 3, stride=stride, padding=padding)
        y = conv(x)
        ref = np.asarray(_dense_conv_ref(dense, conv.weight, conv.bias,
                                         stride=stride, padding=padding))
        out = np.asarray(y.todense())
        assert out.shape == ref.shape
        # active output positions match the dense conv (incl. bias); the
        # remaining positions are zero in the sparse result
        active = np.abs(np.asarray(y.data)).sum(-1) > 0
        idxs = np.asarray(y.indices)[active]
        for (n, d, h, w) in idxs:
            np.testing.assert_allclose(out[n, d, h, w], ref[n, d, h, w],
                                       rtol=1e-4, atol=1e-4)

    def test_output_coords_are_window_cover(self):
        """Every input point must land in ceil-div windows: the sparse
        output active set equals the dense conv's nonzero support for a
        no-bias conv with all-ones weights and positive inputs."""
        rng = np.random.default_rng(6)
        dense = np.zeros((1, 5, 5, 5, 1), np.float32)
        dense[0, 1, 2, 3, 0] = 1.0
        dense[0, 4, 4, 4, 0] = 2.0
        x = jsparse.BCOO.fromdense(jnp.asarray(dense), n_dense=1)
        w = jnp.ones((2, 2, 2, 1, 1), jnp.float32)
        y = snn.functional.conv3d(x, w, stride=2, padding=1)
        out = np.asarray(y.todense())
        ref = np.asarray(_dense_conv_ref(dense, w, None, stride=2, padding=1))
        np.testing.assert_allclose(out, ref, rtol=1e-5)

    def test_capacity_capped_by_output_volume(self):
        """Stacked strided convs must not compound stored rows by K per
        layer (ADVICE r3 medium): output capacity is capped at
        min(nnz*K, prod(out_dims)+1), and a Conv3D->Conv3D chain still
        matches the dense oracle at active outputs."""
        rng = np.random.default_rng(11)
        dense, x = _random_sparse(rng, N=1, D=8, H=8, W=8, C=2, nnz=40)
        paddle.seed(9)
        c1 = snn.Conv3D(2, 3, 3, stride=2, padding=1)
        c2 = snn.Conv3D(3, 4, 3, stride=2, padding=1)
        y1 = c1(x)
        # out volume 1*4*4*4 = 64; candidates = 40*27 = 1080 -> capped
        assert y1.data.shape[0] == 65
        y2 = c2(y1)
        # second layer: nnz*K = 65*27 = 1755, out volume 1*2*2*2=8 -> 9
        assert y2.data.shape[0] == 9
        ref1 = _dense_conv_ref(dense, c1.weight, c1.bias, stride=2,
                               padding=1)
        # dense chain oracle: conv over the dense intermediate restricted
        # to y1's active set (sparse semantics: absent rows contribute 0)
        act1 = np.zeros(ref1.shape, np.float32)
        active1 = np.abs(np.asarray(y1.data)).sum(-1) > 0
        idx1 = np.asarray(y1.indices)
        for i in range(idx1.shape[0]):
            n, d, h, w = idx1[i]
            if active1[i] and d < act1.shape[1]:
                act1[n, d, h, w] = np.asarray(y1.data)[i]
        ref2 = np.asarray(_dense_conv_ref(act1, c2.weight, c2.bias,
                                          stride=2, padding=1))
        out2 = np.asarray(y2.todense())
        active2 = np.abs(np.asarray(y2.data)).sum(-1) > 0
        for (n, d, h, w) in np.asarray(y2.indices)[active2]:
            np.testing.assert_allclose(out2[n, d, h, w], ref2[n, d, h, w],
                                       rtol=1e-4, atol=1e-4)

    def test_jit_compiles(self):
        rng = np.random.default_rng(7)
        _, x = _random_sparse(rng, nnz=8)
        paddle.seed(4)
        conv = snn.Conv3D(4, 4, 2, stride=2)
        y = jax.jit(lambda v: snn.functional.conv3d(
            x, v, stride=2).data.sum())(conv.weight)
        assert np.isfinite(float(y))


class TestMaxPool3D:
    def test_matches_dense_pool_at_active_outputs(self):
        rng = np.random.default_rng(8)
        # positive values so dense max-pool (which sees zeros) agrees with
        # sparse max over stored points at windows containing points
        dense, _ = _random_sparse(rng, nnz=25)
        dense = np.abs(dense) + 0.1 * (np.abs(dense).sum(-1, keepdims=True) > 0)
        x = jsparse.BCOO.fromdense(jnp.asarray(dense), n_dense=1)
        pool = snn.MaxPool3D(2, stride=2)
        y = pool(x)
        xd = jnp.transpose(jnp.asarray(dense), (0, 4, 1, 2, 3))
        ref = F.max_pool3d(xd, 2, stride=2)
        ref = np.asarray(jnp.transpose(ref, (0, 2, 3, 4, 1)))
        out = np.asarray(y.todense())
        active = np.abs(np.asarray(y.data)).sum(-1) > 0
        idxs = np.asarray(y.indices)[active]
        assert len(idxs)
        for (n, d, h, w) in idxs:
            np.testing.assert_allclose(out[n, d, h, w], ref[n, d, h, w],
                                       rtol=1e-5)


class TestBatchNormAndActs:
    def test_batch_norm_normalizes_values(self):
        rng = np.random.default_rng(9)
        dense, x = _random_sparse(rng, nnz=40)
        bn = snn.BatchNorm(4)
        bn.train()
        y = bn(x)
        v = np.asarray(y.data, np.float64)
        np.testing.assert_allclose(v.mean(0), 0, atol=1e-4)
        np.testing.assert_allclose(v.std(0), 1, atol=1e-2)
        # moving stats moved toward the batch stats
        assert not np.allclose(np.asarray(bn._mean), 0)

    def test_batch_norm_eval_uses_moving_stats(self):
        rng = np.random.default_rng(10)
        _, x = _random_sparse(rng, nnz=40)
        bn = snn.BatchNorm(4)
        bn.train(); bn(x)
        bn.eval()
        mean_before = np.asarray(bn._mean).copy()
        bn(x)
        np.testing.assert_allclose(np.asarray(bn._mean), mean_before)

    def test_relu_family(self):
        rng = np.random.default_rng(11)
        _, x = _random_sparse(rng, nnz=15)
        for layer, fn in [(snn.ReLU(), lambda v: np.maximum(v, 0)),
                          (snn.ReLU6(), lambda v: np.clip(v, 0, 6)),
                          (snn.LeakyReLU(0.1),
                           lambda v: np.where(v >= 0, v, 0.1 * v))]:
            y = layer(x)
            np.testing.assert_allclose(np.asarray(y.data),
                                       fn(np.asarray(x.data)), rtol=1e-6)

    def test_activations_accept_generic_sparse_tensors(self):
        """sparse.nn.ReLU keeps working on any-rank COO/CSR tensors (the
        pre-conv-stack behavior; review finding: it had narrowed to 5-D)."""
        import paddle_tpu.sparse as sp
        dense = jnp.asarray([[-1.0, 0.0, 2.0], [3.0, -4.0, 0.0]])
        coo = sp.to_sparse_coo(dense)
        y = snn.ReLU()(coo)
        np.testing.assert_allclose(np.asarray(y.todense()),
                                   np.maximum(np.asarray(dense), 0))
        csr = sp.sparse_csr_tensor([0, 2, 3], [0, 2, 1],
                                   [-1.0, 2.0, -3.0], (2, 3))
        z = snn.LeakyReLU(0.1)(csr)
        np.testing.assert_allclose(
            np.asarray(z.todense()),
            np.where(np.asarray(csr.todense()) >= 0,
                     np.asarray(csr.todense()),
                     0.1 * np.asarray(csr.todense())), rtol=1e-6)
        with pytest.raises(TypeError, match="sparse tensor"):
            snn.ReLU()(jnp.ones((2, 3)))

    def test_max_pool_integer_values(self):
        """Integer-valued volumes pool without the finfo crash (review
        finding)."""
        dense = np.zeros((1, 4, 4, 4, 1), np.int32)
        dense[0, 0, 0, 0, 0] = 7
        dense[0, 1, 1, 1, 0] = 3
        x = jsparse.BCOO.fromdense(jnp.asarray(dense), n_dense=1)
        y = snn.functional.max_pool3d(x, 2, stride=2)
        out = np.asarray(y.todense())
        assert out[0, 0, 0, 0, 0] == 7

    def test_softmax_channels(self):
        rng = np.random.default_rng(12)
        _, x = _random_sparse(rng, nnz=10)
        y = snn.Softmax()(x)
        np.testing.assert_allclose(np.asarray(y.data).sum(-1), 1, rtol=1e-5)


class TestPaddingRowChaining:
    """Strided Conv3D output carries capacity-padding rows (out-of-range
    indices); downstream ops must treat them as absent (review finding:
    they previously polluted BatchNorm stats and SubmConv3D lookups)."""

    def _chain_input(self):
        rng = np.random.default_rng(20)
        dense = np.zeros((1, 6, 6, 6, 3), np.float32)
        pts = rng.choice(6 * 6 * 6, 15, replace=False)
        for p in pts:
            d, r = divmod(int(p), 36)
            h, w = divmod(r, 6)
            dense[0, d, h, w] = rng.normal(size=3) + 0.5
        return dense, jsparse.BCOO.fromdense(jnp.asarray(dense), n_dense=1)

    def test_conv3d_padding_rows_are_out_of_range(self):
        dense, x = self._chain_input()
        paddle.seed(6)
        conv = snn.Conv3D(3, 4, 3, stride=2, padding=1)
        y = conv(x)
        idxs = np.asarray(y.indices)
        shape = np.asarray(y.shape[:4])
        in_range = (idxs >= 0).all(1) & (idxs < shape).all(1)
        # padding rows exist (capacity > active set) and carry zero values
        assert (~in_range).any()
        np.testing.assert_allclose(np.asarray(y.data)[~in_range], 0)

    def test_bias_does_not_accumulate_at_origin(self):
        dense, x = self._chain_input()
        paddle.seed(6)
        conv = snn.Conv3D(3, 4, 3, stride=2, padding=1,
                          bias_attr=paddle.nn.initializer.Constant(5.0))
        y = conv(x)
        out = np.asarray(y.todense())
        ref = np.asarray(_dense_conv_ref(dense, conv.weight, conv.bias,
                                         stride=2, padding=1))
        # origin cell must match the dense conv exactly — no padding-bias
        # pileup at (0,0,0,0)
        np.testing.assert_allclose(out[0, 0, 0, 0], ref[0, 0, 0, 0],
                                   rtol=1e-4, atol=1e-4)

    def test_conv_bn_subm_chain_matches_dense_oracle(self):
        dense, x = self._chain_input()
        paddle.seed(7)
        conv = snn.Conv3D(3, 4, 2, stride=2)
        bn = snn.BatchNorm(4)
        bn.train()
        subm = snn.SubmConv3D(4, 4, 3)
        y = subm(bn(conv(x)))
        out = np.asarray(y.todense())

        # oracle: same chain on the densified tensors, masked to the
        # active set at each sparse stage
        h1 = np.asarray(_dense_conv_ref(dense, conv.weight, conv.bias,
                                        stride=2))
        y1 = np.asarray(conv(x).todense())
        active1 = np.abs(y1).sum(-1, keepdims=True) > 0
        # bn oracle over active rows of the conv output
        rows = y1[active1[..., 0]]
        mean = rows.mean(0)
        var = rows.var(0)
        h2 = (y1 - mean) / np.sqrt(var + 1e-5) * active1
        h3 = np.asarray(_dense_conv_ref(
            h2, subm.weight, subm.bias, padding=1))
        np.testing.assert_allclose(out, h3 * active1, rtol=1e-3, atol=1e-3)

    def test_activations_keep_padding_rows_zero(self):
        dense, x = self._chain_input()
        paddle.seed(8)
        conv = snn.Conv3D(3, 4, 3, stride=2, padding=1)
        y = conv(x)
        idxs = np.asarray(y.indices)
        shape = np.asarray(y.shape[:4])
        pad_rows = ~((idxs >= 0).all(1) & (idxs < shape).all(1))
        for layer in (snn.Softmax(), snn.ReLU6(), snn.LeakyReLU(0.2)):
            z = layer(y)
            np.testing.assert_allclose(np.asarray(z.data)[pad_rows], 0)


class TestEndToEnd:
    # ISSUE 14 tier-1 budget audit: 30 training iterations over 8
    # separately-built BCOO graphs cost ~4 minutes — by far the most
    # expensive test in the suite, and the 870s tier-1 window was
    # truncating exactly here.  The operators' correctness, gradients
    # and jit behaviour stay pinned fast by TestConv3D / TestSubmConv3D
    # (incl. test_jit_and_grad) and the dense-oracle chain tests; this
    # end-to-end soak runs outside the window.
    @pytest.mark.slow
    def test_sparse_cnn_trains(self):
        """SubmConv3D -> BatchNorm -> ReLU -> global sum readout learns a
        2-class point-cloud problem end-to-end under jit."""
        rng = np.random.default_rng(13)
        xs, labels = [], []
        for i in range(8):
            dense = np.zeros((1, 6, 6, 6, 2), np.float32)
            cls = i % 2
            # class decides WHERE mass concentrates
            lo, hi = (0, 3) if cls == 0 else (3, 6)
            for _ in range(10):
                d, h, w = rng.integers(lo, hi, 3)
                dense[0, d, h, w] = rng.normal(size=2) + 1.0
            xs.append(jsparse.BCOO.fromdense(jnp.asarray(dense), n_dense=1))
            labels.append(cls)

        paddle.seed(5)
        conv = snn.SubmConv3D(2, 8, 3)
        head_w = jnp.asarray(rng.normal(size=(8 + 3, 2)) * 0.1, jnp.float32)

        def logits(w, b, hw, x):
            y = snn.functional.subm_conv3d(x, w, b)
            feat = jnp.maximum(y.data, 0).mean(0)
            # position summary: mean active coordinate (normalized)
            pos = x.indices[:, 1:].astype(jnp.float32).mean(0) / 6.0
            return jnp.concatenate([feat, pos]) @ hw

        def loss_fn(params):
            w, b, hw = params
            ls = [F.cross_entropy(logits(w, b, hw, x)[None],
                                  jnp.asarray([c]))
                  for x, c in zip(xs, labels)]
            return jnp.stack(ls).mean()

        params = (conv.weight, conv.bias, head_w)
        val0 = float(loss_fn(params))
        g_fn = jax.value_and_grad(loss_fn)
        for _ in range(30):
            l, g = g_fn(params)
            params = jax.tree.map(lambda p, gg: p - 0.5 * gg, params, g)
        assert float(l) < val0 * 0.5, (val0, float(l))
