"""Layer system tests (model: reference Layer API tests in
test/legacy_test/test_imperative_layers.py etc.)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu
import paddle_tpu.nn as nn
from paddle_tpu.nn import functional_call, state


class MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc1 = nn.Linear(4, 8)
        self.fc2 = nn.Linear(8, 2)
        self.act = nn.ReLU()

    def forward(self, x):
        return self.fc2(self.act(self.fc1(x)))


def test_parameters_enumeration():
    m = MLP()
    names = [n for n, _ in m.named_parameters()]
    assert names == ["fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"]
    assert len(m.parameters()) == 4
    assert m.fc1.weight.shape == (4, 8)


def test_state_dict_roundtrip():
    m = MLP()
    sd = m.state_dict()
    m2 = MLP()
    missing, unexpected = m2.set_state_dict(sd)
    assert not missing and not unexpected
    for k in sd:
        np.testing.assert_array_equal(np.asarray(m2.state_dict()[k]),
                                      np.asarray(sd[k]))


def test_attribute_routing():
    m = MLP()
    w0 = m.fc1.weight
    m.fc1.weight = jnp.zeros_like(w0)
    assert "weight" in m.fc1._parameters
    assert float(jnp.sum(jnp.abs(m.fc1.weight))) == 0.0


def test_train_eval_mode():
    m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    m.eval()
    x = jnp.ones((2, 4))
    y1, y2 = m(x), m(x)
    np.testing.assert_array_equal(np.asarray(y1), np.asarray(y2))
    m.train()
    assert m[1].training


def test_hooks():
    m = MLP()
    calls = []
    h = m.register_forward_post_hook(lambda layer, inp, out: calls.append(1))
    m(jnp.ones((1, 4)))
    assert calls == [1]
    h.remove()
    m(jnp.ones((1, 4)))
    assert calls == [1]


def test_sublayers_and_apply():
    m = MLP()
    assert len(m.sublayers()) == 3
    seen = []
    m.apply(lambda l: seen.append(type(l).__name__))
    assert "MLP" in seen and "Linear" in seen


def test_to_dtype():
    m = MLP()
    m.to(dtype="bfloat16")
    assert m.fc1.weight.dtype == jnp.bfloat16


def test_functional_call_pure():
    m = MLP()
    params, buffers = state(m)
    x = jnp.ones((3, 4))
    out1, _ = functional_call(m, params, buffers, (x,))
    zeroed = {k: jnp.zeros_like(v) for k, v in params.items()}
    out0, _ = functional_call(m, zeroed, buffers, (x,))
    assert float(jnp.sum(jnp.abs(out0))) == 0.0
    # module unchanged after functional call with zeros
    out_again = m(x)
    np.testing.assert_allclose(np.asarray(out1), np.asarray(out_again), rtol=1e-6)


def test_batchnorm_buffers_update():
    bn = nn.BatchNorm2D(3)
    x = jnp.asarray(np.random.randn(4, 3, 5, 5).astype(np.float32)) + 2.0
    params, buffers = state(bn)
    assert "._mean" in "".join(buffers) or "_mean" in buffers
    out, new_buffers = functional_call(bn, params, buffers, (x,), train=True)
    # running mean moved toward batch mean (paddle momentum 0.9)
    assert abs(float(new_buffers["_mean"][0])) > 0.0
    # eval mode uses stats, no update
    out2, nb2 = functional_call(bn, params, new_buffers, (x,), train=False)
    np.testing.assert_allclose(np.asarray(nb2["_mean"]),
                               np.asarray(new_buffers["_mean"]))


def test_grad_through_functional_call():
    m = MLP()
    params, buffers = state(m)
    x = jnp.ones((3, 4))
    y = jnp.zeros((3,), jnp.int32)

    def loss_fn(p):
        out, _ = functional_call(m, p, buffers, (x,))
        return nn.functional.cross_entropy(out, y)

    g = jax.grad(loss_fn)(params)
    assert set(g.keys()) == set(params.keys())
    assert all(np.isfinite(np.asarray(v)).all() for v in g.values())
    # fc1 grad nonzero
    assert float(jnp.sum(jnp.abs(g["fc1.weight"]))) > 0


def test_jit_functional_call_no_leak():
    m = MLP()
    params, buffers = state(m)

    @jax.jit
    def fwd(p, x):
        out, _ = functional_call(m, p, buffers, (x,))
        return out

    out = fwd(params, jnp.ones((2, 4)))
    assert out.shape == (2, 2)
    # layer attributes are still concrete (no tracer leak)
    assert isinstance(m.fc1.weight, jax.Array)
    _ = m(jnp.ones((2, 4)))  # eager still works


def test_shared_sublayer_weight_tying():
    """Tied sublayers must appear once in state (reference pattern: tied
    input/output embeddings in GPT)."""

    class Tied(nn.Layer):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(10, 4)
            self.head = self.emb  # tied

        def forward(self, x):
            h = self.emb(x)
            return h @ self.head.weight.T

    m = Tied()
    params, buffers = state(m)
    assert list(params.keys()) == ["emb.weight"]
    out, _ = functional_call(m, params, buffers, (jnp.asarray([[1, 2]]),))
    assert out.shape == (1, 2, 10)
    g = jax.grad(lambda p: jnp.sum(
        functional_call(m, p, buffers, (jnp.asarray([[1, 2]]),))[0] ** 2))(params)
    assert set(g.keys()) == {"emb.weight"}


def test_dropout_under_jit_requires_rng():
    m = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    params, buffers = state(m)
    with pytest.raises(RuntimeError, match="RNG context"):
        jax.jit(lambda p, x: functional_call(m, p, buffers, (x,))[0])(
            params, jnp.ones((2, 4)))
    # with rng it works and differs across keys
    f = jax.jit(lambda p, x, k: functional_call(m, p, buffers, (x,), rng=k)[0])
    o1 = f(params, jnp.ones((2, 4)), jax.random.key(0))
    o2 = f(params, jnp.ones((2, 4)), jax.random.key(1))
    assert not np.allclose(np.asarray(o1), np.asarray(o2))


def test_orthogonal_and_dirac_initializers():
    import paddle_tpu.nn.initializer as I
    key = jax.random.PRNGKey(0)
    w = I.Orthogonal().init(key, (8, 4), jnp.float32)
    np.testing.assert_allclose(np.asarray(w.T @ w), np.eye(4), atol=1e-5)
    w2 = I.Orthogonal(gain=2.0).init(key, (4, 8), jnp.float32)
    np.testing.assert_allclose(np.asarray(w2 @ w2.T), 4 * np.eye(4),
                               atol=1e-4)
    k = I.Dirac().init(key, (2, 2, 3, 3), jnp.float32)
    # impulse at kernel center, channel-matched
    x = jnp.asarray(np.random.RandomState(0).randn(1, 2, 5, 5), jnp.float32)
    import paddle_tpu.nn.functional as F
    y = F.conv2d(x, k, padding=1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), rtol=1e-5,
                               atol=1e-5)
