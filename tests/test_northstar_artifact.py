"""AOT_NORTHSTAR.json integrity: the committed scale-proof artifact
(round-5 VERDICT item 1) keeps its load-bearing claims.

The artifact is produced by scripts/aot_northstar.py on a virtual
128-device mesh; this test pins that the committed file says what the
notes/README quote: all three legs compiled through the SPMD
partitioner, passed their HBM-fit verdicts, and the hybrid legs carry
the pipeline's collective-permute ring plus (for MoE) expert-dispatch
all-to-alls.
"""

import json
import os

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load():
    with open(os.path.join(ROOT, "AOT_NORTHSTAR.json")) as f:
        return json.load(f)


def test_all_legs_compiled_and_fit():
    art = _load()
    assert art["n_virtual_devices"] == 128
    for leg in ("gpt_6_7b_hybrid", "llama_7b_semi_auto",
                "gpt_moe_hybrid"):
        d = art[leg]
        assert d["status"] == "done", (leg, d["status"])
        assert d["fit_verdict"] == "PASS", leg
        assert d["compile_s"] > 0, leg
        assert d["spmd_collectives_per_step"]["total"] > 0, leg
        hbm = d["hbm_accounting"]
        assert hbm["total_per_device"] <= 0.85 * hbm["v5p_hbm"], leg
        # the GB presentation block mirrors the byte block, sans bools
        assert "fit" not in d["hbm_accounting_gb"], leg


def test_structural_collectives():
    art = _load()
    gpt = art["gpt_6_7b_hybrid"]["spmd_collectives_per_step"]
    assert gpt.get("collective-permute", 0) >= 2, gpt   # pp ring
    moe = art["gpt_moe_hybrid"]["spmd_collectives_per_step"]
    assert moe.get("all-to-all", 0) >= 2, moe           # expert dispatch
    assert moe.get("collective-permute", 0) >= 2, moe   # pp ring


def test_gpt_leg_is_the_baseline_config():
    d = _load()["gpt_6_7b_hybrid"]
    assert d["config"]["num_params"] > 6.5e9
    assert d["config"]["seq"] == 2048
    assert d["mesh"] == {"dp": 2, "sharding": 2, "pp": 4, "mp": 8}
    assert d["config"]["zero_stage"] == 1 and d["config"]["sp"]


def test_convergence_soak_artifact_complete_when_committed():
    """CONVERGENCE_SOAK.json is quoted by the README as evidence of the
    full-stack soak (pre-registered target + mid-run kill/restore with
    exact resume equivalence).  When the artifact is present it must be
    a COMPLETE run carrying that evidence — a partial status:running
    snapshot must never ship as the canonical artifact.  (Run 1 lives in
    CONVERGENCE_SOAK_r1_calibration.json with its own honest verdict.)"""
    path = os.path.join(ROOT, "CONVERGENCE_SOAK.json")
    import subprocess
    tracked = subprocess.run(
        ["git", "ls-files", "--error-unmatch", path],
        cwd=ROOT, capture_output=True).returncode == 0
    if not (tracked and os.path.exists(path)):
        import pytest
        pytest.skip("soak artifact not committed yet (run in progress)")
    with open(path) as f:
        d = json.load(f)
    assert d.get("status") == "done", d.get("status")
    v = d["verdict"]
    assert v["target_met"] is True and v["resume_exact"] is True, v
    assert v["final_val_ce"] < d["target_val_ce_nats"], v
    assert d["resume_equivalence"]["equal"] is True
