"""Launcher tests: env contract, watchdog, elastic restart, spawn.

Mirrors the reference's launcher tests (test/legacy_test/test_run.py
pattern): shell out to ``python -m paddle_tpu.distributed.launch`` with a
tiny script, assert the env contract and restart behavior.  Workers are
plain python (no JAX import) so tests stay fast.
"""

import os
import subprocess
import sys
import textwrap

import pytest

from conftest import requires_modern_jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(tmp_path, script_body, extra_args=(), returncode=0):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(script_body))
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["PYTHONPATH"] = REPO
    env["PADDLE_PORT"] = "62000"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(tmp_path / "log"), *extra_args, str(script)],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=120)
    assert r.returncode == returncode, (r.stdout, r.stderr)
    return r


def test_launch_env_contract(tmp_path):
    _run_launch(tmp_path, """
        import os, json
        info = {k: os.environ[k] for k in
                ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
                 "PADDLE_TRAINER_ENDPOINTS", "PADDLE_CURRENT_ENDPOINT",
                 "PADDLE_LOCAL_RANK")}
        with open(f"out_{os.environ['PADDLE_TRAINER_ID']}.json", "w") as f:
            json.dump(info, f)
    """, extra_args=("--nproc_per_node", "2"))
    import json
    o0 = json.load(open(tmp_path / "out_0.json"))
    o1 = json.load(open(tmp_path / "out_1.json"))
    assert o0["PADDLE_TRAINERS_NUM"] == "2"
    assert o0["PADDLE_TRAINER_ENDPOINTS"] == o1["PADDLE_TRAINER_ENDPOINTS"]
    assert len(o0["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 2
    assert o0["PADDLE_CURRENT_ENDPOINT"] != o1["PADDLE_CURRENT_ENDPOINT"]
    assert {o0["PADDLE_TRAINER_ID"], o1["PADDLE_TRAINER_ID"]} == {"0", "1"}


def test_launch_elastic_restart_then_success(tmp_path):
    """Worker fails on first run, succeeds after restart (the max_restart
    loop — reference: ElasticManager/controller watch)."""
    _run_launch(tmp_path, """
        import os, sys
        marker = "attempt.txt"
        n = int(open(marker).read()) if os.path.exists(marker) else 0
        open(marker, "w").write(str(n + 1))
        restart = int(os.environ["PADDLE_RESTART_COUNT"])
        sys.exit(1 if n == 0 else 0)
    """, extra_args=("--max_restart", "2"))
    assert (tmp_path / "attempt.txt").read_text() == "2"


def test_launch_gives_up_after_max_restart(tmp_path):
    r = _run_launch(tmp_path, """
        import sys
        sys.exit(7)
    """, extra_args=("--max_restart", "1"), returncode=7)
    assert "giving up" in r.stderr


def test_launch_worker_logs(tmp_path):
    _run_launch(tmp_path, """
        print("hello from worker")
    """)
    log = (tmp_path / "log" / "workerlog.0").read_text()
    assert "hello from worker" in log


def test_spawn_function():
    from paddle_tpu.distributed.spawn import spawn
    import multiprocessing as mp

    q = mp.get_context("spawn").Queue()
    spawn(_spawn_target, args=(q,), nprocs=2)
    got = sorted([q.get(timeout=10), q.get(timeout=10)])
    assert got == [0, 1]


def _spawn_target(q):
    import os
    q.put(int(os.environ["PADDLE_TRAINER_ID"]))


def test_launch_two_process_jax_distributed_allreduce(tmp_path):
    """End-to-end: launcher spawns 2 REAL processes, each boots
    jax.distributed off the env contract, and an all_reduce crosses the
    process boundary (VERDICT r1 item 7 — the env contract was previously
    only unit-tested single-process)."""
    import socket
    import subprocess
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runner = os.path.join(repo, "tests", "runners", "allreduce_runner.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)           # workers pin their own 1-dev CPU
    env["PADDLE_TPU_REPO"] = repo
    log_dir = str(tmp_path / "log")
    r = subprocess.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--log_dir", log_dir, "--max_restart", "0", runner],
        env=env, cwd=repo, capture_output=True, text=True, timeout=300)
    logs = ""
    for i in (0, 1):
        p = os.path.join(log_dir, f"workerlog.{i}")
        if os.path.exists(p):
            logs += open(p).read()
    assert r.returncode == 0, (r.stderr[-500:], logs[-1000:])
    assert logs.count("ALLREDUCE_OK") == 2, logs[-1000:]


def test_launch_four_process_collective_breadth(tmp_path):
    """4 REAL processes drive all_gather / broadcast(src=2) /
    reduce_scatter / barrier across the process boundary (round-2 review:
    eager multi-process semantics beyond 2-proc all_reduce were
    unexercised)."""
    import socket
    import subprocess
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runner = os.path.join(repo, "tests", "runners", "collectives4_runner.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env["PADDLE_TPU_REPO"] = repo
    log_dir = str(tmp_path / "log")
    r = subprocess.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "4", "--master", f"127.0.0.1:{port}",
         "--log_dir", log_dir, "--max_restart", "0", runner],
        env=env, cwd=repo, capture_output=True, text=True, timeout=420)
    logs = ""
    for i in range(4):
        p = os.path.join(log_dir, f"workerlog.{i}")
        if os.path.exists(p):
            logs += open(p).read()
    assert r.returncode == 0, (r.stderr[-500:], logs[-1200:])
    assert logs.count("COLLECTIVES4_OK") == 4, logs[-1200:]


def test_rpc_two_processes(tmp_path):
    """distributed.rpc across 2 real processes via the launcher env
    contract (reference: python/paddle/distributed/rpc)."""
    import subprocess
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runner = os.path.join(repo, "tests", "runners", "rpc_runner.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PADDLE_TPU_REPO"] = repo
    from conftest import free_local_port
    env["PADDLE_PORT"] = str(free_local_port())
    log_dir = str(tmp_path / "log")
    r = subprocess.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--log_dir", log_dir,
         "--max_restart", "0", runner],
        env=env, cwd=repo, capture_output=True, text=True, timeout=180)
    logs = ""
    for i in (0, 1):
        p = os.path.join(log_dir, f"workerlog.{i}")
        if os.path.exists(p):
            logs += open(p).read()
    assert r.returncode == 0, (r.stderr[-400:], logs[-800:])
    assert logs.count("RPC_OK") == 2, logs[-800:]


def test_launch_heartbeat_detects_hang(tmp_path):
    """A worker that stops heartbeating is treated as hung, killed, and the
    job restarts; the retry succeeds (elastic hang detection — reference:
    ElasticManager heartbeats)."""
    import subprocess
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runner = os.path.join(repo, "tests", "runners", "hang_runner.py")
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PADDLE_TPU_REPO"] = repo
    log_dir = str(tmp_path / "log")
    r = subprocess.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "1", "--log_dir", log_dir,
         "--heartbeat_timeout", "2", "--max_restart", "1", runner],
        env=env, cwd=repo, capture_output=True, text=True, timeout=120)
    assert r.returncode == 0, (r.stderr[-500:],)
    assert "heartbeat stale" in r.stderr
    logs = open(os.path.join(log_dir, "workerlog.0")).read()
    assert "HANG_RUNNER_OK" in logs


@pytest.mark.parametrize("start_n,end_n", [(8, 4), (4, 8)])
def test_elastic_remesh_restart(tmp_path, start_n, end_n):
    """Elastic re-mesh restart, both directions (round-2 VERDICT item 8 +
    scale-OUT): the run starts on a start_n-device mesh, the device count
    changes (crash after writing the elastic devices file), the watchdog
    relaunches, the worker rebuilds an end_n-device mesh and resumes from
    the distributed checkpoint via reshard-on-load — final weights equal
    the uninterrupted serial trajectory (dp math is degree-invariant for
    a fixed global batch)."""
    devfile = tmp_path / "devices.txt"
    devfile.write_text(str(start_n))
    script = """
        import os, sys
        import numpy as np
        n = int(os.environ.get("PADDLE_ELASTIC_DEVICE_COUNT", "%START%"))
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        import re
        flags = re.sub(r"--xla_force_host_platform_device_count=[0-9]+", "",
                       os.environ.get("XLA_FLAGS", ""))
        os.environ["XLA_FLAGS"] = \\
            (flags + " --xla_force_host_platform_device_count=%d" % n).strip()
        import jax
        jax.config.update("jax_platforms", "cpu")
        try:
            jax.config.update("jax_num_cpu_devices", n)
        except AttributeError:
            pass  # jax < 0.5: the XLA_FLAGS line above sets the count
        import jax.extend.backend as _jeb
        _jeb.clear_backends()
        jax.config.update("jax_default_matmul_precision", "highest")
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P
        import paddle_tpu
        from paddle_tpu.distributed.auto_parallel import (ProcessMesh,
                                                          Shard, Replicate,
                                                          shard_tensor)
        from paddle_tpu.distributed.checkpoint import (save_state_dict,
                                                       load_state_dict)

        assert len(jax.devices()) == n, (n, jax.devices())
        mesh = ProcessMesh(np.arange(n), dim_names=["dp"])
        restart = int(os.environ.get("PADDLE_RESTART_COUNT", "0"))

        rs = np.random.RandomState(0)
        xs = rs.randn(16, 8).astype(np.float32)      # fixed global batch
        TOTAL = 6

        ckpt = "ckpt"
        if restart == 0:
            w = shard_tensor(np.zeros((8, 1), np.float32), mesh,
                             [Replicate()])
            start = 0
        else:
            got = load_state_dict(
                {"w": jax.ShapeDtypeStruct((8, 1), jnp.float32),
                 "step": jax.ShapeDtypeStruct((), jnp.int32)}, ckpt)
            # reshard-on-load: shards written by the pre-resize mesh land
            # on the new device count (either direction)
            w = shard_tensor(np.asarray(got["w"]), mesh, [Replicate()])
            start = int(np.asarray(got["step"]))

        x_sh = shard_tensor(xs, mesh, [Shard(0)])    # batch over dp

        @jax.jit
        def step(w, x):
            # mean-squared push toward 1.0: grad averaged over the global
            # batch -> identical math at any dp degree
            y = x @ w
            g = x.T @ (y - 1.0) / x.shape[0]
            return w - 0.1 * g

        w_cur = w
        for s in range(start, TOTAL):
            w_cur = step(w_cur, x_sh)
            if restart == 0 and s == 2:
                save_state_dict({"w": w_cur,
                                 "step": jnp.asarray(s + 1, jnp.int32)},
                                ckpt)
                with open(os.environ["ELASTIC_DEVFILE"], "w") as f:
                    f.write("%END%")   # the slice is resized
                os._exit(1)

        # oracle: uninterrupted serial trajectory
        w_ref = np.zeros((8, 1), np.float32)
        for _ in range(TOTAL):
            y = xs @ w_ref
            w_ref = w_ref - 0.1 * (xs.T @ (y - 1.0) / xs.shape[0])
        np.testing.assert_allclose(np.asarray(w_cur), w_ref,
                                   rtol=1e-5, atol=1e-6)
        with open("elastic_result.txt", "w") as f:
            f.write(f"OK ndev={n} restart={restart}")
    """
    import textwrap
    script = script.replace("%START%", str(start_n)).replace(
        "%END%", str(end_n))
    sp = tmp_path / "worker.py"
    sp.write_text(textwrap.dedent(script))
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PYTHONPATH"] = REPO
    env["PADDLE_PORT"] = "62400"
    env["ELASTIC_DEVFILE"] = str(devfile)
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(tmp_path / "log"),
         "--max_restart", "2",
         "--elastic_devices_file", str(devfile), str(sp)],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=300)
    assert r.returncode == 0, (r.stdout, r.stderr,
                               open(tmp_path / "log" / "workerlog.0").read()
                               if (tmp_path / "log" / "workerlog.0").exists()
                               else "")
    out = (tmp_path / "elastic_result.txt").read_text()
    assert out == f"OK ndev={end_n} restart=1", out


@requires_modern_jax
def test_launch_two_process_hybrid_trainer(tmp_path):
    """The FULL hybrid GPT trainer (dp x mp x pp x ZeRO, sp) runs across
    2 real processes with the pipeline axis split on the process
    boundary (round-4 VERDICT Weak #5: the hybrid trainer had never run
    multi-process; global_rank was hardcoded 0).  The runner asserts
    global_rank == process_index, pp-stage process ownership, vocab-
    scale init loss and a decreasing loss; here we additionally pin
    SPMD consistency: both ranks report identical losses."""
    import re
    import socket
    import subprocess
    import sys as _sys
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    runner = os.path.join(repo, "tests", "runners", "hybrid2_runner.py")
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["PADDLE_TPU_REPO"] = repo
    log_dir = str(tmp_path / "log")
    r = subprocess.run(
        [_sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", "--master", f"127.0.0.1:{port}",
         "--log_dir", log_dir, "--max_restart", "0", runner],
        env=env, cwd=repo, capture_output=True, text=True, timeout=600)
    logs = ""
    for i in (0, 1):
        p = os.path.join(log_dir, f"workerlog.{i}")
        if os.path.exists(p):
            logs += open(p).read()
    assert r.returncode == 0, (r.stderr[-500:], logs[-1200:])
    marks = re.findall(r"HYBRID2_OK rank=(\d) loss=([\d.]+)->([\d.]+)",
                       logs)
    assert len(marks) == 2, logs[-1200:]
    (r0, a0, b0), (r1, a1, b1) = sorted(marks)
    assert {r0, r1} == {"0", "1"}
    assert (a0, b0) == (a1, b1), marks   # SPMD: same program, same loss


def test_hybrid_mesh_uses_ici_aware_assignment(monkeypatch):
    """HybridCommunicateGroup must route device->mesh assignment through
    mesh_utils.create_device_mesh (ICI-topology-aware; AXIS_ORDER ends
    with mp so the chattiest axis rides the innermost physical ring) —
    not a naive enumeration reshape (round-4 VERDICT missing #3)."""
    import jax
    from unittest import mock
    from paddle_tpu.distributed import topology as topo
    from jax.experimental import mesh_utils

    seen = {}
    real = mesh_utils.create_device_mesh

    def spy(shape, devices=None, **kw):
        seen["shape"] = tuple(shape)
        seen["n"] = len(devices)
        return real(shape, devices=devices, **kw)

    with mock.patch.object(mesh_utils, "create_device_mesh", spy):
        hcg = topo.HybridCommunicateGroup(
            dp_degree=2, mp_degree=2, pp_degree=2,
            devices=jax.devices()[:8])
    assert seen["n"] == 8
    assert seen["shape"][-1] == 2 and len(seen["shape"]) == 6
    assert hcg.get_mesh().axis_names[-1] == "mp"
    assert hcg.get_mesh().devices.size == 8
