"""Launcher tests: env contract, watchdog, elastic restart, spawn.

Mirrors the reference's launcher tests (test/legacy_test/test_run.py
pattern): shell out to ``python -m paddle_tpu.distributed.launch`` with a
tiny script, assert the env contract and restart behavior.  Workers are
plain python (no JAX import) so tests stay fast.
"""

import os
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launch(tmp_path, script_body, extra_args=(), returncode=0):
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(script_body))
    env = {k: v for k, v in os.environ.items()
           if not k.startswith(("JAX_", "XLA_"))}
    env["PYTHONPATH"] = REPO
    env["PADDLE_PORT"] = "62000"
    r = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--log_dir", str(tmp_path / "log"), *extra_args, str(script)],
        env=env, cwd=str(tmp_path), capture_output=True, text=True,
        timeout=120)
    assert r.returncode == returncode, (r.stdout, r.stderr)
    return r


def test_launch_env_contract(tmp_path):
    _run_launch(tmp_path, """
        import os, json
        info = {k: os.environ[k] for k in
                ("PADDLE_TRAINER_ID", "PADDLE_TRAINERS_NUM",
                 "PADDLE_TRAINER_ENDPOINTS", "PADDLE_CURRENT_ENDPOINT",
                 "PADDLE_LOCAL_RANK")}
        with open(f"out_{os.environ['PADDLE_TRAINER_ID']}.json", "w") as f:
            json.dump(info, f)
    """, extra_args=("--nproc_per_node", "2"))
    import json
    o0 = json.load(open(tmp_path / "out_0.json"))
    o1 = json.load(open(tmp_path / "out_1.json"))
    assert o0["PADDLE_TRAINERS_NUM"] == "2"
    assert o0["PADDLE_TRAINER_ENDPOINTS"] == o1["PADDLE_TRAINER_ENDPOINTS"]
    assert len(o0["PADDLE_TRAINER_ENDPOINTS"].split(",")) == 2
    assert o0["PADDLE_CURRENT_ENDPOINT"] != o1["PADDLE_CURRENT_ENDPOINT"]
    assert {o0["PADDLE_TRAINER_ID"], o1["PADDLE_TRAINER_ID"]} == {"0", "1"}


def test_launch_elastic_restart_then_success(tmp_path):
    """Worker fails on first run, succeeds after restart (the max_restart
    loop — reference: ElasticManager/controller watch)."""
    _run_launch(tmp_path, """
        import os, sys
        marker = "attempt.txt"
        n = int(open(marker).read()) if os.path.exists(marker) else 0
        open(marker, "w").write(str(n + 1))
        restart = int(os.environ["PADDLE_RESTART_COUNT"])
        sys.exit(1 if n == 0 else 0)
    """, extra_args=("--max_restart", "2"))
    assert (tmp_path / "attempt.txt").read_text() == "2"


def test_launch_gives_up_after_max_restart(tmp_path):
    r = _run_launch(tmp_path, """
        import sys
        sys.exit(7)
    """, extra_args=("--max_restart", "1"), returncode=7)
    assert "giving up" in r.stderr


def test_launch_worker_logs(tmp_path):
    _run_launch(tmp_path, """
        print("hello from worker")
    """)
    log = (tmp_path / "log" / "workerlog.0").read_text()
    assert "hello from worker" in log


def test_spawn_function():
    from paddle_tpu.distributed.spawn import spawn
    import multiprocessing as mp

    q = mp.get_context("spawn").Queue()
    spawn(_spawn_target, args=(q,), nprocs=2)
    got = sorted([q.get(timeout=10), q.get(timeout=10)])
    assert got == [0, 1]


def _spawn_target(q):
    import os
    q.put(int(os.environ["PADDLE_TRAINER_ID"]))
