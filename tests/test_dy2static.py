"""dy2static conversion oracles: an eager function with data-dependent
Python control flow must match its converted static (jitted) version
(reference test model: test/dygraph_to_static/ — each op-level converter
is checked eager-vs-static).

Eager oracle = run the ORIGINAL function on concrete numpy-backed arrays
(Python control flow executes natively); static = to_static(fn) under jit
where args are tracers, forcing the lax.cond/while_loop path.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu
from paddle_tpu import jit as pjit
from paddle_tpu.jit.dy2static import (convert_to_static, Dy2StaticError)


def _check(fn, *argsets, atol=1e-6):
    """converted+jitted fn == original eager fn on every argset."""
    static = pjit.to_static(fn)
    for args in argsets:
        want = fn(*args)
        got = static(*args)
        jax.tree.map(
            lambda w, g: np.testing.assert_allclose(
                np.asarray(w), np.asarray(g), atol=atol, rtol=1e-6),
            want, got)


def test_data_dependent_if():
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = -x
        return y + 1.0

    _check(f, (jnp.ones(4),), (-jnp.ones(4),))


def test_if_without_else():
    def f(x):
        y = x + 1.0
        if y.sum() > 3.0:
            y = y * 10.0
        return y

    _check(f, (jnp.ones(4),), (jnp.zeros(4) - 5.0,))


def test_elif_chain():
    def f(x):
        s = x.sum()
        if s > 10.0:
            r = x * 3.0
        elif s > 0.0:
            r = x * 2.0
        else:
            r = x * 0.5
        return r

    _check(f, (jnp.full(4, 5.0),), (jnp.full(4, 0.5),), (-jnp.ones(4),))


def test_both_branches_return():
    def f(x):
        if x.mean() > 0:
            return x - x.mean()
        else:
            return x + 1.0

    _check(f, (jnp.arange(4.0),), (-jnp.arange(4.0) - 1,))


def test_bool_ops_in_condition():
    def f(x):
        if x.sum() > 0 and x.max() < 10.0:
            y = x + 5.0
        else:
            y = x - 5.0
        if not (x.min() > -100.0) or x.sum() > 1.0:
            y = y * 2.0
        return y

    _check(f, (jnp.ones(3),), (jnp.full(3, 20.0),), (-jnp.ones(3),))


def test_tensor_while_loop():
    def f(x):
        n = jnp.asarray(0, jnp.int32)
        s = x
        while s.sum() < 100.0:
            s = s * 2.0
            n = n + 1
        return s, n

    _check(f, (jnp.ones(4),), (jnp.full(4, 30.0),))


def test_nested_if_in_while():
    def f(x):
        s = x
        while s.sum() < 50.0:
            if s.max() > 4.0:
                s = s + 10.0
            else:
                s = s * 3.0
        return s

    _check(f, (jnp.ones(4),), (jnp.full(4, 5.0),))


def test_for_range_traced_bound():
    def f(x, n):
        acc = jnp.zeros_like(x)
        for i in range(n):
            acc = acc + x * (i + 1)
        return acc

    # n as a traced int forces the while_loop path; concrete python int
    # in eager runs the plain range
    static = pjit.to_static(f)
    x = jnp.arange(3.0)
    for n in (0, 1, 4):
        want = f(x, n)
        got = static(x, jnp.asarray(n, jnp.int32))
        np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                                   atol=1e-6)


def test_python_control_flow_still_python():
    """Concrete (non-tensor) predicates keep exact Python semantics
    through the converted function — including short-circuit."""
    def f(x, flag):
        if flag:
            y = x + 1.0
        else:
            y = x - 1.0
        # short-circuit: the second operand would raise if evaluated
        if (not flag) or x.shape[0] > 0:
            y = y * 2.0
        return y

    conv = convert_to_static(f)
    x = jnp.ones(2)
    np.testing.assert_allclose(np.asarray(conv(x, True)),
                               np.asarray(f(x, True)))
    np.testing.assert_allclose(np.asarray(conv(x, False)),
                               np.asarray(f(x, False)))


def test_break_in_tensor_loop_converts():
    """break via flag rewriting (reference BreakContinueTransformer):
    converted/traced == original eager."""
    def f(x):
        s = x
        n = jnp.asarray(0, jnp.int32)
        while s.sum() < 100.0:
            s = s * 2.0
            if s.max() > 11.0:
                break
            n = n + 1
        return s, n

    _check(f, (jnp.ones(4),), (jnp.full(4, 50.0),))


def test_continue_in_tensor_loop_converts():
    def f(x):
        i = jnp.asarray(0, jnp.int32)
        acc = jnp.zeros_like(x)
        while i < 6:
            i = i + 1
            if jnp.sum(x) * i % 2.0 < 1.0:
                continue
            acc = acc + x * i
        return acc, i

    _check(f, (jnp.ones(3),), (jnp.full(3, 2.0),))


def test_break_and_continue_combined():
    def f(x):
        i = jnp.asarray(0, jnp.int32)
        total = jnp.zeros((), x.dtype)
        while i < 100:
            i = i + 1
            if i % 3 == 0:
                continue
            if total > 20.0:
                break
            total = total + x.sum()
        return total, i

    _check(f, (jnp.ones(4),), (jnp.full(4, 0.5),))


def test_single_branch_return_now_converts():
    """Previously out-of-subset; the function-level ReturnTransformer
    rewrite handles it (round-3 late addition)."""
    def f(x):
        if x.sum() > 0:
            return x
        x = x * 2.0
        return x

    _check(f, (jnp.ones(4),), (-jnp.ones(4),))


def test_return_without_tail_clear_error():
    """No tail return -> out of subset: the rewrite cannot prove every
    path binds the value; the traced if still errors clearly."""
    def f(x):
        if x.sum() > 0:
            return x * 2.0
        x = x + 1.0   # falls off the end on this path

    static = pjit.to_static(f)
    with pytest.raises(Dy2StaticError, match="return"):
        static(jnp.ones(4))


def test_layer_forward_converted():
    import paddle_tpu.nn as nn

    class Gate(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)

        def forward(self, x):
            h = self.lin(x)
            if h.sum() > 0:
                return h * 2.0
            else:
                return h - 1.0

    paddle_tpu.seed(0)
    layer = Gate()
    x = jnp.ones((2, 4))
    eager = layer(x)            # converted forward, concrete-value path...
    static = pjit.to_static(layer)
    out = static(x)             # ...vs traced lax.cond path
    np.testing.assert_allclose(np.asarray(eager), np.asarray(out),
                               atol=1e-6)


def test_loop_carried_shape_change_clear_error():
    def f(x):
        while x.sum() < 10.0:
            x = jnp.concatenate([x, x])
        return x

    static = pjit.to_static(f)
    with pytest.raises((Dy2StaticError, TypeError)):
        static(jnp.ones(2))


def test_one_sided_binding_materializes_placeholder():
    """A variable bound in only one branch gets the reference's
    UndefinedVar/fill-constant placeholder on the other path: the taken
    branch's value when the predicate holds, zeros otherwise (eager
    Python would raise NameError on the false path — documented
    deviation, same as the reference)."""
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        return y  # noqa: F821 — defined only on one path

    static = pjit.to_static(f)
    np.testing.assert_allclose(np.asarray(static(jnp.ones(4))),
                               2.0 * np.ones(4))
    np.testing.assert_allclose(np.asarray(static(-jnp.ones(4))),
                               np.zeros(4))


def test_enable_to_static_toggle():
    def f(x):
        if x.sum() > 0:
            return x * 2.0
        else:
            return -x

    static = pjit.to_static(f)
    try:
        pjit.enable_to_static(False)
        out = static(jnp.ones(2))   # runs the original eagerly
        np.testing.assert_allclose(np.asarray(out), 2 * np.ones(2))
    finally:
        pjit.enable_to_static(True)


def test_save_load_converted_function(tmp_path):
    """jit.save must export the CONVERTED program (lax.cond), not the raw
    Python function (which cannot trace data-dependent branches)."""
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = -x
        return y

    static = pjit.to_static(f)
    path = str(tmp_path / "dy2s_model")
    from paddle_tpu.static import InputSpec
    pjit.save(static, path, input_spec=[InputSpec((4,), "float32")])
    loaded = pjit.load(path)
    for x in (jnp.ones(4), -jnp.ones(4)):
        np.testing.assert_allclose(np.asarray(loaded(x)),
                                   np.asarray(f(x)), atol=1e-6)


def test_break_nested_while_converts():
    """break inside a while nested in another converted while (the inner
    loop's flags first bind inside the outer body — they must carry)."""
    def f(x):
        i = jnp.asarray(0, jnp.int32)
        total = jnp.zeros((), x.dtype)
        while i < 3:
            i = i + 1
            j = jnp.asarray(0, jnp.int32)
            while j < 10:
                j = j + 1
                if j > 2:
                    break
            total = total + j.astype(x.dtype) * x.sum()
        return total, i

    _check(f, (jnp.ones(4),), (jnp.full(4, 0.25),))


def test_break_loop_eager_python_path():
    """Flag-rewritten loops keep exact Python semantics on concrete
    values (the convert_while eager branch)."""
    def f(x):
        s = x
        while s.sum() < 100.0:
            s = s * 2.0
            if s.max() > 50.0:
                break
        return s

    out = convert_to_static(f)(np.ones(4))
    want = f(np.ones(4))
    np.testing.assert_allclose(np.asarray(out), np.asarray(want))


def test_for_range_break_continue():
    """for-range with break/continue over a traced bound: the increment
    runs as the not-broken epilogue (python for semantics — continue
    advances the index, break freezes it)."""
    def f(x, n):
        acc = jnp.zeros_like(x)
        last = jnp.asarray(-1, jnp.int32)
        for i in range(n):
            if i % 2 == 1:
                continue
            if jnp.sum(acc) > 6.0:
                break
            acc = acc + x * (i + 1)
            last = jnp.asarray(i, jnp.int32)
        return acc, last

    static = pjit.to_static(f)
    x = jnp.ones(2)
    for n in (0, 1, 5, 9):
        want = f(x, n)
        got = static(x, jnp.asarray(n, jnp.int32))
        np.testing.assert_allclose(np.asarray(want[0]), np.asarray(got[0]),
                                   atol=1e-6)
        assert int(want[1]) == int(np.asarray(got[1])), (n, want[1], got[1])


def test_read_before_write_one_sided_clear_error():
    """A branch that READS a one-sided variable before writing it cannot
    be materialized (the probe fails on the Undefined read) — the clear
    Dy2StaticError diagnosis must surface, not a raw JAX error."""
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = y + 1.0  # noqa: F821 — read before any binding
        return y

    static = pjit.to_static(f)
    with pytest.raises(Dy2StaticError):
        static(jnp.ones(4))


def test_read_before_write_attribute_clear_error():
    """Attribute access on a one-sided variable (y.sum() before binding)
    surfaces the clear diagnosis, not a raw AttributeError."""
    def f(x):
        if x.sum() > 0:
            y = x * 2.0
        else:
            y = y.sum() * x  # noqa: F821 — read before any binding
        return y

    static = pjit.to_static(f)
    with pytest.raises(Dy2StaticError, match="one path"):
        static(jnp.ones(4))


def test_ternary_ifexp_converts():
    def f(x):
        y = (x * 2.0) if x.sum() > 0 else (-x)
        z = 1.0 if x.max() > 100.0 else 0.5   # stays cond-dispatched
        return y * z

    _check(f, (jnp.ones(4),), (-jnp.ones(4),))


def test_assert_on_tensor_clear_error_and_python_assert_kept():
    def f(x):
        assert x.sum() > 0, "neg"
        return x * 2.0

    static = pjit.to_static(f)
    with pytest.raises(Dy2StaticError, match="checkify"):
        static(jnp.ones(4))
    # concrete path keeps python assert semantics
    conv = convert_to_static(f)
    np.testing.assert_allclose(np.asarray(conv(np.ones(4))), 2 * np.ones(4))
    with pytest.raises(AssertionError, match="neg"):
        conv(np.full(4, -1.0))


def test_print_converts_to_debug_print(capfd):
    def f(x):
        print("value:", x.sum())
        return x + 1.0

    static = pjit.to_static(f)
    out = static(jnp.ones(3))
    np.testing.assert_allclose(np.asarray(out), 2 * np.ones(3))
    import jax
    jax.effects_barrier()
    captured = capfd.readouterr()
    assert "3.0" in captured.out, captured.out


def test_assert_msg_lazy_and_print_shadow_respected():
    """Assert messages stay LAZY (only evaluated on failure), and a
    locally rebound ``print`` is NOT hijacked by the conversion."""
    def f(x):
        errors = []
        assert x.shape[0] == 3, errors[0]   # msg would raise if evaluated
        return x * 2.0

    conv = convert_to_static(f)
    np.testing.assert_allclose(np.asarray(conv(np.ones(3))), 2 * np.ones(3))

    def g(x):
        logs = []
        print = logs.append   # noqa: A001 — deliberate shadow
        print("recorded")
        if x.sum() > 0:
            x = x * 1.0
        return x, logs

    conv_g = convert_to_static(g)
    _, logs = conv_g(np.ones(2))
    assert logs == ["recorded"]


def test_early_return_in_if_converts():
    """General early-return rewriting (reference ReturnTransformer): a
    return buried in a nested if converts; remaining statements are
    skipped on the returned path."""
    def f(x):
        s = x * 1.0
        if s.sum() > 10.0:
            if s.max() > 6.0:
                return s * 100.0
            s = s + 1.0
        s = s * 2.0
        return s

    _check(f, (jnp.ones(4),),            # no return taken
           (jnp.full(4, 3.0),),          # outer if, inner not -> +1 *2
           (jnp.full(4, 7.0),))          # early return *100


def test_early_return_in_while_converts():
    """Early return inside a tensor while: the flag stops the loop (a
    spinning cond whose vars stop updating would otherwise hang)."""
    def f(x):
        s = x
        n = jnp.asarray(0, jnp.int32)
        while s.sum() < 1000.0:
            s = s * 2.0
            n = n + 1
            if n >= 3:
                return s + 0.5
        return s

    _check(f, (jnp.ones(4),),            # early return at n==3
           (jnp.full(4, 300.0),))        # cond exits first


def test_early_return_mixed_paths_match_python():
    def f(x, k):
        t = x.sum()
        if t < 0:
            return -x
        while t < 10.0:
            t = t + k
            if t > 5.0:
                return x * t
        return x * 0.0

    static = pjit.to_static(f)
    for xv, kv in ((jnp.ones(3), 2.0), (-jnp.ones(3), 1.0),
                   (jnp.full(3, 4.0), 0.5)):
        want = f(xv, float(kv))
        got = static(xv, jnp.asarray(kv, jnp.float32))
        np.testing.assert_allclose(np.asarray(want), np.asarray(got),
                                   atol=1e-5, rtol=1e-5)


def test_while_else_with_return_keeps_python_semantics():
    """while/else: python skips the else clause on return; the rewrite
    must not convert such loops (review r3 repro)."""
    def f(x):
        log = []
        n = 3
        while n > 0:
            return (x, tuple(log))
        else:  # noqa: SIM500
            log.append("else ran")
        return (x * 0.0, tuple(log))

    conv = convert_to_static(f)
    want = f(np.ones(2))
    got = conv(np.ones(2))
    assert want[1] == got[1] == ()
    np.testing.assert_allclose(np.asarray(want[0]), np.asarray(got[0]))


# -------------------- for over a tensor (round-4) ------------------------

def test_for_over_tensor_accumulates():
    """for x in <jax array> converts to ONE traced while body (not
    shape[0] unrolled copies) and matches eager python iteration."""
    def fn(xs):
        acc = jnp.zeros(xs.shape[1:])
        for row in xs:
            acc = acc + row * row
        return acc

    xs = jnp.asarray(np.random.RandomState(0).randn(6, 4).astype(np.float32))
    _check(fn, (xs,))

    # structural proof of non-unrolling: the jaxpr carries a while_loop
    static = pjit.to_static(fn)
    jaxpr = jax.make_jaxpr(static)(xs)
    assert "while" in str(jaxpr), "for-over-tensor should lower to while"


def test_for_over_tensor_break_continue():
    def fn(xs, t):
        acc = jnp.zeros(())
        for v in xs:
            if v < 0:
                continue
            if acc > t:
                break
            acc = acc + v
        return acc

    rs = np.random.RandomState(1)
    xs = jnp.asarray(rs.randn(8).astype(np.float32))
    _check(fn, (xs, jnp.asarray(0.5)), (xs, jnp.asarray(100.0)))


def test_for_over_python_list_stays_python():
    """Non-array iterables keep the plain Python for (unrolled trace)."""
    def fn(x):
        acc = x
        for c in [1.0, 2.0, 3.0]:
            acc = acc + c
        return acc

    _check(fn, (jnp.asarray(1.0),))
    static = pjit.to_static(fn)
    jaxpr = jax.make_jaxpr(static)(jnp.asarray(1.0))
    assert "while" not in str(jaxpr)      # unrolled, no loop primitive


def test_for_over_tensor_first_bound_inside():
    """The loop element var and a body-local both first bind inside the
    converted loop — convert_while materializes them."""
    def fn(xs):
        total = jnp.zeros(())
        for item in xs:
            doubled = item * 2
            total = total + doubled
        return total

    xs = jnp.asarray(np.arange(5, dtype=np.float32))
    _check(fn, (xs,))


def test_for_over_tensor_2d_rows_matmul():
    def fn(xs, w):
        out = jnp.zeros((xs.shape[0], w.shape[1]))
        i = 0
        for row in xs:
            out = out.at[i].set(row @ w)
            i = i + 1
        return out

    rs = np.random.RandomState(2)
    xs = jnp.asarray(rs.randn(3, 4).astype(np.float32))
    w = jnp.asarray(rs.randn(4, 2).astype(np.float32))
    _check(fn, (xs, w), atol=1e-5)


# -------------------- try/except passthrough (round-4) -------------------

def test_try_except_passthrough_with_converted_if_inside():
    """Converted tensor control flow INSIDE a try body still converts;
    the try/except itself stays Python (trace-time semantics)."""
    def fn(x):
        try:
            if jnp.sum(x) > 0:
                y = x * 2.0
            else:
                y = x - 1.0
        except ValueError:       # never fires under tracing
            y = x
        return y

    _check(fn, (jnp.ones(3),), (-jnp.ones(3),))


def test_try_except_catches_python_error_at_trace_time():
    """A genuine Python exception raised while tracing follows Python
    try semantics — the handler's (traced) computation is what lands in
    the program."""
    def fn(x):
        try:
            bad = x.shape[99]          # IndexError at trace time
            y = x * bad
        except IndexError:
            y = x + 1.0
        return y

    _check(fn, (jnp.ones(3),))


def test_try_finally_with_early_return_rewrite():
    """return-flag rewriting descends into Try bodies (the guarded-flag
    walk handles Try); finally still runs."""
    ran = []

    def fn(x):
        try:
            if jnp.sum(x) > 0:
                return x * 2.0
        finally:
            ran.append(1)
        return x - 1.0

    _check(fn, (jnp.ones(3),), (-jnp.ones(3),))
    assert ran


def test_for_over_tensor_nested_in_converted_while():
    """Composition: for-over-tensor INSIDE a tensor-dependent while."""
    def fn(xs, n):
        total = jnp.zeros(())
        i = jnp.zeros((), jnp.int32)
        while i < n:
            for v in xs:
                total = total + v
            i = i + 1
        return total

    xs = jnp.asarray(np.arange(4, dtype=np.float32))
    _check(fn, (xs, jnp.asarray(3, jnp.int32)),
           (xs, jnp.asarray(0, jnp.int32)))


def test_converted_if_inside_for_over_tensor():
    def fn(xs):
        pos = jnp.zeros(())
        for v in xs:
            if v > 0:
                pos = pos + v
        return pos

    rs = np.random.RandomState(3)
    _check(fn, (jnp.asarray(rs.randn(7).astype(np.float32)),))


def test_for_over_tensor_zero_length():
    """Zero-length leading dim: the converted loop runs zero iterations
    (matches Python's empty-for)."""
    def fn(xs):
        acc = jnp.zeros(())
        for v in xs:
            acc = acc + v
        return acc

    _check(fn, (jnp.zeros((0, 3)).sum(axis=1),))
