"""Differential fuzzing for dy2static: seeded random programs over the
supported subset (nested tensor-dependent if/while/for-range,
for-over-tensor, try/except/finally passthrough, break/continue and
and/or conditions) must produce identical results
eagerly and converted+jitted — the reference validates its
ProgramTranslator the same way, with a fixed corpus of dygraph models.

The generator emits SOURCE (the converter works on AST), always
pre-binds every assigned name, bounds every loop with a counter, and
keeps arithmetic contraction-free so eager/compiled float drift stays
within tolerance.
"""

import textwrap

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu  # noqa: F401
from paddle_tpu import jit as pjit

N_PROGRAMS = 60


def _gen_block(rng, depth, indent, loop_id, in_for=False):
    """Returns (lines, loop_id).  Every branch/loop body assigns at least
    one of acc/t (converted ifs need a carried local)."""
    pad = "    " * indent
    lines = []
    n_stmts = rng.randint(1, 4)
    for _ in range(n_stmts):
        kind = rng.choice(
            ["assign", "if", "while", "for", "ret", "for_tensor", "try"],
            p=[0.32, 0.21, 0.11, 0.11, 0.08, 0.09, 0.08] if depth > 0
            else [1.0, 0, 0, 0, 0, 0, 0])
        if kind == "ret":
            # early return matching the tail structure (acc, t) — but
            # never inside a for (out of the return-rewrite subset)
            if in_for:
                kind = "assign"   # fall through to a plain statement
            else:
                c = round(float(rng.uniform(0.5, 2.0)), 3)
                lines.append(
                    pad + f"if t > {round(float(rng.uniform(0, 2)), 2)}:")
                lines.append(pad + f"    return acc * {c}, t")
                continue
        if kind == "assign":
            c = round(float(rng.uniform(0.2, 1.5)), 3)
            stmt = rng.choice([
                f"acc = acc + x * {c}",
                f"acc = acc * {round(float(rng.uniform(0.6, 0.95)), 3)}",
                f"t = t * 0.9 + {c}",
                f"t = t + acc.sum() * 0.01",
            ])
            lines.append(pad + stmt)
        elif kind == "if":
            cond = _gen_cond(rng)
            lines.append(pad + f"if {cond}:")
            b, loop_id = _gen_block(rng, depth - 1, indent + 1, loop_id,
                                    in_for)
            lines.extend(b)
            if rng.rand() < 0.7:
                lines.append(pad + "else:")
                b, loop_id = _gen_block(rng, depth - 1, indent + 1,
                                        loop_id, in_for)
                lines.extend(b)
        elif kind == "while":
            loop_id += 1
            i = f"i{loop_id}"
            bound = rng.randint(2, 5)
            cond = _gen_cond(rng)
            lines.append(pad + f"{i} = jnp.asarray(0, jnp.int32)")
            lines.append(pad + f"while ({i} < {bound}) and ({cond}):")
            lines.append(pad + f"    {i} = {i} + 1")
            b, loop_id = _gen_block(rng, depth - 1, indent + 1, loop_id,
                                    in_for)
            lines.extend(b)
            if rng.rand() < 0.3:
                lines.append(pad + f"    if t > {round(float(rng.uniform(1, 4)), 2)}:")
                lines.append(pad + "        break")
        elif kind == "for_tensor":
            # round-4 statement form: for-over-tensor converts to ONE
            # traced while_loop; break/continue ride the flag rewrite
            loop_id += 1
            v = f"v{loop_id}"
            lines.append(pad + f"for {v} in x:")
            jump = rng.rand()
            if jump < 0.25:
                lines.append(pad + f"    if {v} > "
                             f"{round(float(rng.uniform(-0.5, 0.5)), 2)}:")
                lines.append(pad + "        continue")
            elif jump < 0.45:
                lines.append(pad + f"    if acc.sum() > "
                             f"{round(float(rng.uniform(3, 8)), 2)}:")
                lines.append(pad + "        break")
            c = round(float(rng.uniform(0.05, 0.4)), 3)
            lines.append(pad + f"    acc = acc + {v} * {c}")
            b, loop_id = _gen_block(rng, depth - 1, indent + 1, loop_id,
                                    in_for=True)
            lines.extend(b)
        elif kind == "try":
            # round-4 statement form: try/except passthrough (the body
            # never raises, the handler must stay dead in BOTH modes);
            # finally always runs
            lines.append(pad + "try:")
            b, loop_id = _gen_block(rng, depth - 1, indent + 1, loop_id,
                                    in_for)
            lines.extend(b)   # _gen_block always emits >= 1 statement
            lines.append(pad + "except (ValueError, RuntimeError):")
            lines.append(pad + "    t = t + 1000.0")
            if rng.rand() < 0.5:
                lines.append(pad + "finally:")
                lines.append(
                    pad + f"    t = t * "
                    f"{round(float(rng.uniform(0.9, 0.999)), 3)}")
        else:  # for-range
            loop_id += 1
            k = f"k{loop_id}"
            n = rng.randint(2, 5)
            lines.append(pad + f"for {k} in range({n}):")
            jump = rng.rand()
            if jump < 0.25:
                lines.append(pad + f"    if {k} == 1:")
                lines.append(pad + "        continue")
            elif jump < 0.5:
                lines.append(pad + f"    if acc.sum() > "
                             f"{round(float(rng.uniform(3, 8)), 2)}:")
                lines.append(pad + "        break")
            b, loop_id = _gen_block(rng, depth - 1, indent + 1, loop_id,
                                    in_for=True)
            lines.extend(b)
    return lines, loop_id


def _gen_cond(rng):
    atoms = [
        f"t > {round(float(rng.uniform(-1, 3)), 3)}",
        f"acc.sum() < {round(float(rng.uniform(1, 10)), 3)}",
        f"x.max() > {round(float(rng.uniform(-1, 1)), 3)}",
    ]
    a = rng.choice(atoms)
    if rng.rand() < 0.4:
        b = rng.choice(atoms)
        op = rng.choice(["and", "or"])
        return f"({a}) {op} ({b})"
    if rng.rand() < 0.15:
        return f"not ({a})"
    return a


def _gen_program(seed):
    rng = np.random.RandomState(seed)
    body, _ = _gen_block(rng, depth=2, indent=1, loop_id=0)
    src = "def f(x):\n" \
          "    acc = jnp.zeros_like(x)\n" \
          "    t = jnp.sum(x) * 0.1\n" + \
          "\n".join(body) + "\n" \
          "    return acc, t\n"
    return src


# ISSUE 14 tier-1 budget audit: 60 generated programs x 3 inputs cost
# ~10s inside the 870s tier-1 window; the converter's supported subset
# stays pinned fast by tests/test_dy2static.py's 43 directed tests.
# The differential soak runs outside the window.
@pytest.mark.slow
def test_dy2static_differential_fuzz():
    failures = []
    import linecache
    for seed in range(N_PROGRAMS):
        src = _gen_program(seed)
        ns = {"jnp": jnp}
        filename = f"<fuzz{seed}>"
        # exec'd code has no file: register the source in linecache so
        # inspect.getsource (which the AST converter relies on) finds it
        linecache.cache[filename] = (len(src), None,
                                     src.splitlines(True), filename)
        exec(compile(src, filename, "exec"), ns)
        f = ns["f"]
        static = pjit.to_static(f)
        for j, scale in enumerate((0.5, -0.8, 2.0)):
            x = jnp.asarray(
                np.random.RandomState(100 + seed * 3 + j)
                .uniform(-1, 1, (4,)).astype(np.float32) * scale)
            want = f(x)              # eager: python control flow
            got = static(x)          # converted + jitted
            for w, g in zip(want, got):
                if not np.allclose(np.asarray(w), np.asarray(g),
                                   rtol=2e-4, atol=2e-4):
                    failures.append(
                        (seed, j, np.asarray(w), np.asarray(g),
                         textwrap.indent(src, "  ")))
    assert not failures, failures[0]
