"""Round-4 third adversarial-sweep batch: distributed p2p batch API,
role makers, ASGD, global initializer, device stream facades,
amp.debugging, jit logging knobs, paddle.batch, cuda-rng aliases, mesh
globals, and the generated Tensor-method compat surface.
"""

import os
import warnings

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.initializer as I
import paddle_tpu.optimizer as opt
from paddle_tpu.nn.layer import ParamAttr


class TestDistributedAdditions:
    def test_is_available(self):
        assert paddle.distributed.is_available() is True

    def test_p2pop_validation(self):
        op = paddle.distributed.P2POp(paddle.distributed.isend,
                                      jnp.ones(2), 1)
        assert op.peer == 1
        with pytest.raises(ValueError):
            paddle.distributed.P2POp(print, jnp.ones(2), 1)

    def test_batch_isend_irecv_stance(self):
        op = paddle.distributed.P2POp(paddle.distributed.irecv,
                                      jnp.ones(2), 0)
        with pytest.raises(RuntimeError, match="ppermute"):
            paddle.distributed.batch_isend_irecv([op])
        with pytest.raises(ValueError):
            paddle.distributed.batch_isend_irecv([])
        with pytest.raises(ValueError):
            paddle.distributed.batch_isend_irecv(["nope"])

    def test_set_get_mesh(self):
        mesh = paddle.distributed.ProcessMesh([0], dim_names=["x"])
        paddle.distributed.set_mesh(mesh)
        assert paddle.distributed.get_mesh() is mesh
        paddle.distributed.set_mesh(None)
        assert paddle.distributed.get_mesh() is None


class TestRoleMakers:
    def test_user_defined(self):
        fleet = paddle.distributed.fleet
        rm = fleet.UserDefinedRoleMaker(
            current_id=1, role=fleet.Role.WORKER, worker_num=4,
            server_endpoints=["h:1", "h:2"])
        assert rm.is_worker() and not rm.is_server()
        assert rm.worker_index() == 1 and rm.worker_num() == 4
        assert rm.server_num() == 2
        assert not rm.is_first_worker()

    def test_paddlecloud_from_env(self):
        env = {"TRAINING_ROLE": "PSERVER", "PADDLE_PSERVER_ID": "1",
               "PADDLE_PSERVERS_IP_PORT_LIST": "127.0.0.1:1,127.0.0.1:2"}
        old = {k: os.environ.get(k) for k in env}
        os.environ.update(env)
        try:
            rm = paddle.distributed.fleet.PaddleCloudRoleMaker()
            assert rm.is_server()
            assert rm.server_index() == 1
            assert rm.get_pserver_endpoints() == ["127.0.0.1:1",
                                                  "127.0.0.1:2"]
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def test_fleet_init_records_role(self):
        fleet = paddle.distributed.fleet
        rm = fleet.UserDefinedRoleMaker(current_id=0, role=fleet.Role.WORKER,
                                        worker_num=2)
        fleet.init(role_maker=rm)
        assert fleet.is_worker() and not fleet.is_server()


class TestASGD:
    def test_batch_num_1_is_sgd(self):
        params = {"w": jnp.ones(3)}
        o = opt.ASGD(learning_rate=0.1, batch_num=1)
        st = o.init(params)
        p, st = o.update({"w": jnp.full(3, 2.0)}, st, params)
        np.testing.assert_allclose(np.asarray(p["w"]), 0.8, rtol=1e-6)

    def test_average_over_slots(self):
        # averages over gradients SEEN (min(step, m)), not slot capacity:
        # step1 d=2 n=1 -> p=0.8; step2 d=6 n=2 -> 0.8-0.3=0.5;
        # step3 replaces slot0 (2->6): d=10 n=2 -> 0.5-0.5=0.0
        params = {"w": jnp.ones(3)}
        o = opt.ASGD(learning_rate=0.1, batch_num=2)
        st = o.init(params)
        p = params
        p, st = o.update({"w": jnp.full(3, 2.0)}, st, p)
        np.testing.assert_allclose(np.asarray(p["w"]), 0.8, rtol=1e-5)
        p, st = o.update({"w": jnp.full(3, 4.0)}, st, p)
        np.testing.assert_allclose(np.asarray(p["w"]), 0.5, rtol=1e-5)
        p, st = o.update({"w": jnp.full(3, 6.0)}, st, p)
        np.testing.assert_allclose(np.asarray(p["w"]), 0.0, atol=1e-5)

    def test_rejects_bad_batch_num(self):
        with pytest.raises(ValueError):
            opt.ASGD(batch_num=0)


class TestGlobalInitializer:
    def teardown_method(self, m):
        I.set_global_initializer(None, None)

    def test_overrides_defaults_not_explicit_attr(self):
        I.set_global_initializer(I.Constant(0.5), I.Constant(0.25))
        lin = nn.Linear(3, 4)
        assert float(lin.weight[0, 0]) == 0.5
        assert float(lin.bias[0]) == 0.25
        explicit = nn.Linear(3, 4,
                             weight_attr=ParamAttr(initializer=I.Constant(2.0)))
        assert float(explicit.weight[0, 0]) == 2.0

    def test_reset(self):
        I.set_global_initializer(I.Constant(0.5))
        I.set_global_initializer(None, None)
        lin = nn.Linear(3, 4)
        assert float(lin.bias[0]) == 0.0

    def test_type_checked(self):
        with pytest.raises(TypeError):
            I.set_global_initializer("xavier")


class TestMiscTopLevel:
    def test_batch_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            paddle.batch(lambda: iter(range(3)), 0)

    def test_stream_guard_syncs_on_exception(self):
        synced = []

        class S(paddle.device.Stream):
            def synchronize(self):
                synced.append(1)

        with pytest.raises(RuntimeError):
            with paddle.device.stream_guard(S()):
                raise RuntimeError("boom")
        assert synced

    def test_batch_reader(self):
        r = paddle.batch(lambda: iter(range(7)), 3)
        assert [len(b) for b in r()] == [3, 3, 1]
        r = paddle.batch(lambda: iter(range(7)), 3, drop_last=True)
        assert [len(b) for b in r()] == [3, 3]

    def test_cuda_rng_aliases(self):
        st = paddle.get_cuda_rng_state()
        paddle.seed(123)
        a = paddle.rand([3])
        paddle.set_cuda_rng_state(st)
        assert paddle.get_cuda_rng_state() is not None

    def test_compiled_with(self):
        assert paddle.is_compiled_with_cinn() is False
        assert paddle.is_compiled_with_rocm() is False

    def test_jit_logging_knobs_independent(self):
        import logging
        logger = logging.getLogger("paddle_tpu.dy2static")
        paddle.jit.set_verbosity(1)
        paddle.jit.set_code_level(-1)
        assert logger.level == logging.INFO
        paddle.jit.set_code_level(100)
        assert logger.level == logging.DEBUG
        # lowering verbosity must NOT cancel the code-dump level
        paddle.jit.set_verbosity(0)
        assert logger.level == logging.DEBUG
        paddle.jit.set_code_level(-1)
        assert logger.level == logging.WARNING


class TestDeviceStreamFacade:
    def test_stream_event_protocol(self):
        s = paddle.device.Stream()
        e = s.record_event()
        assert e.query() is True
        e2 = paddle.device.Event()
        e2.record(s)
        s.wait_event(e2)
        s2 = paddle.device.Stream()
        s2.wait_stream(s)
        assert s.query() is True

    def test_stream_guard_and_current(self):
        s = paddle.device.current_stream()
        with paddle.device.stream_guard(s) as g:
            assert g is s

    def test_get_available_device(self):
        devs = paddle.device.get_available_device()
        assert isinstance(devs, list) and devs


class TestAmpAdditions:
    def test_supported_flags(self):
        assert paddle.amp.is_bfloat16_supported() is True
        assert paddle.amp.is_float16_supported() is True

    def test_debugging_warn_once_and_check_numerics(self):
        from paddle_tpu.amp import debugging as adbg
        adbg._WARNED[0] = False
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            adbg.enable_operator_stats_collection()
            adbg.disable_operator_stats_collection()
            with adbg.collect_operator_stats():
                pass
        assert len(w) == 1
        out = adbg.check_numerics(jnp.ones(3), "op", "var")
        np.testing.assert_allclose(np.asarray(out), 1.0)

    def test_tensor_checker_toggles(self):
        import jax
        from paddle_tpu.amp import debugging as adbg
        adbg.enable_tensor_checker()
        assert jax.config.jax_debug_nans
        adbg.disable_tensor_checker()
        assert not jax.config.jax_debug_nans


class TestCompatGeneratedMethods:
    @classmethod
    def setup_class(cls):
        from paddle_tpu.compat import enable_tensor_methods
        enable_tensor_methods()

    def test_delegated_functional_methods(self):
        t = jnp.asarray(np.arange(6.0).reshape(2, 3))
        vals, idx = t.topk(2)
        assert vals.shape == (2, 2)
        assert len(t.split(3, axis=1)) == 3
        assert float(t.norm()) == pytest.approx(
            np.linalg.norm(np.arange(6.0)))
        assert t.cast("int32").dtype == jnp.int32
        assert t.flip(0).shape == (2, 3)
        assert t.unbind(0)[0].shape == (3,)
        assert t.broadcast_to([2, 2, 3]).shape == (2, 2, 3)
        assert bool(t.isfinite().all())

    def test_inplace_names_return_result(self):
        t = jnp.ones((2, 2))
        out = t.add_(jnp.ones((2, 2)))
        assert float(out[0, 0]) == 2.0
        assert float(t[0, 0]) == 1.0          # immutability documented
        assert float(t.zero_()[0, 0]) == 0.0

    def test_meta_methods(self):
        t = jnp.ones((2, 3), jnp.float32)
        assert t.element_size() == 4
        assert t.ndimension() == 2
        assert t.is_contiguous() is True
        assert t.contiguous() is t
        assert t.value() is t

    def test_tape_methods_raise_with_guidance(self):
        t = jnp.ones(3)
        with pytest.raises(RuntimeError, match="value_and_grad"):
            t.backward()
        with pytest.raises(RuntimeError, match="custom_vjp"):
            t.register_hook(lambda g: g)
        with pytest.raises(RuntimeError, match="immutable"):
            t.set_value(jnp.zeros(3))
        with pytest.raises(RuntimeError, match="immutable"):
            t.copy_(jnp.zeros(3))

    def test_trace_safe_under_jit(self):
        import jax

        @jax.jit
        def f(x):
            return x.add_(x).norm()

        assert float(f(jnp.ones(4))) == pytest.approx(4.0)
