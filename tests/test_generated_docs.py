"""Generated artifacts (OP_COVERAGE.md, docs/api_reference.md) stay in
sync with the live package surface: regenerate into a temp path and
compare byte-for-byte with the committed file, and assert full coverage
(no MISSING rows)."""

import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(ROOT, "scripts"))


def test_op_coverage_in_sync(tmp_path):
    import gen_op_coverage
    out = tmp_path / "OP_COVERAGE.md"
    gen_op_coverage.main(str(out))
    committed = open(os.path.join(ROOT, "OP_COVERAGE.md")).read()
    assert out.read_text() == committed, \
        "OP_COVERAGE.md is stale — run python scripts/gen_op_coverage.py"
    # no "## Missing in <module>" section may follow the totals row (the
    # round-4 adversarial-sweep prose legitimately contains the word
    # "missing", so match the heading, not the bare word)
    assert "## Missing in" not in committed.split("| **total** |")[1]
    assert "IMPORT FAILED" not in committed


def test_api_reference_in_sync(tmp_path):
    import gen_api_reference
    out = tmp_path / "api_reference.md"
    gen_api_reference.main(str(out))
    committed = open(
        os.path.join(ROOT, "docs", "api_reference.md")).read()
    assert out.read_text() == committed, \
        "api_reference.md is stale — run python scripts/gen_api_reference.py"
    assert "MISSING" not in committed
