"""chunked_softmax_cross_entropy: the no-materialized-logits LM loss.

Oracles: the dense logits + logsumexp CE path (parallel_cross_entropy's
math), forward AND both gradients, f32 and bf16; plus the
GPTForCausalLM.chunked_loss hook against model.loss.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu  # noqa: F401
from paddle_tpu.nn.functional import chunked_softmax_cross_entropy


def _dense_ce(hidden, weight, labels):
    logits = hidden.astype(jnp.float32) @ weight.astype(jnp.float32).T
    m = jnp.max(logits, -1)
    lse = m + jnp.log(jnp.sum(jnp.exp(logits - m[:, None]), -1))
    picked = jnp.take_along_axis(
        logits, labels.astype(jnp.int32)[:, None], 1)[:, 0]
    return lse - picked


@pytest.mark.parametrize("dtype,rtol", [(jnp.float32, 1e-5),
                                        (jnp.bfloat16, 2e-2)])
def test_forward_and_grads_match_dense(dtype, rtol):
    rs = np.random.RandomState(0)
    N, h, V = 24, 16, 40
    hidden = jnp.asarray(rs.randn(N, h), dtype)
    weight = jnp.asarray(rs.randn(V, h) * 0.2, dtype)
    labels = jnp.asarray(rs.randint(0, V, (N,)))

    out = chunked_softmax_cross_entropy(hidden, weight, labels,
                                        n_chunks=5)
    ref = _dense_ce(hidden, weight, labels)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=rtol, atol=rtol)

    def loss_c(hd, w):
        return jnp.mean(chunked_softmax_cross_entropy(hd, w, labels,
                                                      n_chunks=5))

    def loss_d(hd, w):
        return jnp.mean(_dense_ce(hd, w, labels))

    gc = jax.grad(loss_c, argnums=(0, 1))(hidden, weight)
    gd = jax.grad(loss_d, argnums=(0, 1))(hidden, weight)
    for a, b, name in zip(gc, gd, ("hidden", "weight")):
        assert a.dtype == b.dtype == dtype
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=rtol, atol=rtol, err_msg=name)


def test_uneven_vocab_falls_back():
    rs = np.random.RandomState(1)
    hidden = jnp.asarray(rs.randn(6, 8), jnp.float32)
    weight = jnp.asarray(rs.randn(13, 8), jnp.float32)   # 13 % 5 != 0
    labels = jnp.asarray(rs.randint(0, 13, (6,)))
    out = chunked_softmax_cross_entropy(hidden, weight, labels,
                                        n_chunks=5)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(_dense_ce(hidden, weight, labels)),
        rtol=1e-5)


def test_under_jit_and_memory_shape():
    # under jit the scan must stay rolled (no [N, V] intermediate): we
    # can at least assert the lowered text contains a while loop and NO
    # dot with the full-vocab output shape
    rs = np.random.RandomState(2)
    N, h, V, k = 32, 16, 64, 8
    hidden = jnp.asarray(rs.randn(N, h), jnp.float32)
    weight = jnp.asarray(rs.randn(V, h), jnp.float32)
    labels = jnp.asarray(rs.randint(0, V, (N,)))

    def f(hd, w):
        return jnp.mean(chunked_softmax_cross_entropy(hd, w, labels,
                                                      n_chunks=k))

    txt = jax.jit(jax.grad(f, argnums=(0, 1))).lower(hidden, weight) \
        .as_text()
    assert "while" in txt
    assert f"tensor<{N}x{V}xf32>" not in txt, \
        "full-vocab logits materialized despite chunking"


def test_model_chunked_loss_matches_loss():
    from paddle_tpu.models import GPTForCausalLM
    from paddle_tpu.models.gpt import GPTConfig
    paddle_tpu.seed(3)
    cfg = GPTConfig(vocab_size=96, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=32)
    m = GPTForCausalLM(cfg)
    rs = np.random.RandomState(4)
    ids = jnp.asarray(rs.randint(0, 96, (2, 17)))
    x, y = ids[:, :-1], ids[:, 1:]
    dense = float(m.loss(x, y))
    chunked = float(m.chunked_loss(x, y, n_chunks=4))
    assert abs(dense - chunked) < 1e-4, (dense, chunked)


def test_ignore_index_masks_loss_and_grads():
    rs = np.random.RandomState(5)
    N, h, V = 12, 8, 20
    hidden = jnp.asarray(rs.randn(N, h), jnp.float32)
    weight = jnp.asarray(rs.randn(V, h) * 0.2, jnp.float32)
    labels = np.asarray(rs.randint(0, V, (N,)))
    labels[3] = -100
    labels[7] = -100
    lbl = jnp.asarray(labels)

    out = chunked_softmax_cross_entropy(hidden, weight, lbl, n_chunks=4)
    assert float(out[3]) == 0.0 and float(out[7]) == 0.0
    # valid rows match the dense oracle
    ref = _dense_ce(hidden, weight, jnp.where(lbl < 0, 0, lbl))
    keep = labels >= 0
    np.testing.assert_allclose(np.asarray(out)[keep],
                               np.asarray(ref)[keep], rtol=1e-5)
    # ignored rows contribute NO gradient to hidden
    g = jax.grad(lambda hd: jnp.sum(chunked_softmax_cross_entropy(
        hd, weight, lbl, n_chunks=4)))(hidden)
    np.testing.assert_allclose(np.asarray(g)[~keep], 0.0)
    assert np.abs(np.asarray(g)[keep]).sum() > 0
    # dense fallback path masks too
    out_fb = chunked_softmax_cross_entropy(hidden, weight, lbl,
                                           n_chunks=3)  # 20 % 3 != 0
    assert float(out_fb[3]) == 0.0


def test_llama_chunked_loss_matches_loss():
    from paddle_tpu.models.llama import LlamaForCausalLM, llama_tiny
    import paddle_tpu
    paddle_tpu.seed(6)
    m = LlamaForCausalLM(llama_tiny())
    rs = np.random.RandomState(7)
    ids = jnp.asarray(rs.randint(0, 256, (2, 17)))
    x, y = ids[:, :-1], ids[:, 1:]
    dense = float(m.loss(x, y))
    chunked = float(m.chunked_loss(x, y, n_chunks=4))
    assert abs(dense - chunked) < 1e-4, (dense, chunked)


def test_out_of_range_labels_chunked_matches_dense():
    """Out-of-range labels (not ignore_index) clamp to [0, V-1] on BOTH
    paths — before the fix the chunked path silently returned loss = lse
    (picked nothing) while the dense path clamped via take_along_axis:
    two different wrong answers for the same invalid input (ADVICE r5)."""
    rs = np.random.RandomState(9)
    N, h, V = 10, 8, 20
    hidden = jnp.asarray(rs.randn(N, h), jnp.float32)
    weight = jnp.asarray(rs.randn(V, h) * 0.2, jnp.float32)
    labels = np.asarray(rs.randint(0, V, (N,)))
    labels[1] = V + 3          # just past the vocab end -> clamps to V - 1
    labels[4] = 250            # far past -> V - 1
    labels[6] = -7             # negative but NOT ignore_index -> clamps to 0
    labels[8] = -100           # ignore_index stays masked to zero loss
    lbl = jnp.asarray(labels)

    chunked = chunked_softmax_cross_entropy(hidden, weight, lbl, n_chunks=4)
    dense = chunked_softmax_cross_entropy(hidden, weight, lbl, n_chunks=1)
    np.testing.assert_allclose(np.asarray(chunked), np.asarray(dense),
                               rtol=1e-5, atol=1e-6)
    assert float(chunked[8]) == 0.0

    # both agree with an explicitly clamped dense oracle on non-ignored rows
    ref = _dense_ce(hidden, weight,
                    jnp.clip(jnp.where(lbl == -100, 0, lbl), 0, V - 1))
    keep = labels != -100
    np.testing.assert_allclose(np.asarray(chunked)[keep],
                               np.asarray(ref)[keep], rtol=1e-5)

    # and the custom-vjp chunked gradient matches the dense-path gradient
    gc = jax.grad(lambda hd: jnp.sum(chunked_softmax_cross_entropy(
        hd, weight, lbl, n_chunks=4)))(hidden)
    gd = jax.grad(lambda hd: jnp.sum(chunked_softmax_cross_entropy(
        hd, weight, lbl, n_chunks=1)))(hidden)
    np.testing.assert_allclose(np.asarray(gc), np.asarray(gd),
                               rtol=1e-5, atol=1e-6)
