"""Text + audio dataset parser tests over synthesized archives.

Reference: python/paddle/text/datasets/*, python/paddle/audio/datasets/*.
Test model: the vision.datasets synthesized-archive oracles — build tiny
archives in the EXACT reference formats, assert parsing, splits, vocab
and label semantics.
"""

import io
import os
import tarfile
import wave
import zipfile

import numpy as np
import pytest

from paddle_tpu.text import (Conll05st, Imdb, Imikolov, Movielens,
                             UCIHousing, WMT14, WMT16)
from paddle_tpu.audio.datasets import ESC50, TESS, load_wav


# --------------------------------------------------------------- helpers

def _tar_with(tmp_path, name, members):
    path = tmp_path / name
    with tarfile.open(path, "w:gz") as tf:
        for mname, text in members.items():
            data = text.encode()
            info = tarfile.TarInfo(mname)
            info.size = len(data)
            tf.addfile(info, io.BytesIO(data))
    return str(path)


def _write_wav(path, samples, sr=16000):
    with wave.open(str(path), "wb") as w:
        w.setnchannels(1)
        w.setsampwidth(2)
        w.setframerate(sr)
        w.writeframes((np.clip(samples, -1, 1) * 32767)
                      .astype(np.int16).tobytes())


# ------------------------------------------------------------------ text

class TestUCIHousing:
    def test_parse_normalize_split(self, tmp_path):
        rng = np.random.default_rng(0)
        table = rng.normal(size=(10, 14)).astype(np.float32)
        f = tmp_path / "housing.data"
        np.savetxt(f, table)
        tr = UCIHousing(data_file=str(f), mode="train")
        te = UCIHousing(data_file=str(f), mode="test")
        assert len(tr) == 8 and len(te) == 2
        x, y = tr[0]
        assert x.shape == (13,) and y.shape == (1,)
        # normalization: (x - avg) / (max - min) over the whole table
        feats = table[:, :-1]
        want = (feats[0] - feats.mean(0)) / (feats.max(0) - feats.min(0))
        np.testing.assert_allclose(x, want, rtol=1e-5)
        np.testing.assert_allclose(y, table[0, -1:], rtol=1e-6)

    def test_guidance_error(self):
        with pytest.raises(RuntimeError, match="local file"):
            UCIHousing()


class TestImdb:
    def test_labels_shared_vocab_and_modes(self, tmp_path):
        f = _tar_with(tmp_path, "aclImdb.tar.gz", {
            "aclImdb/train/pos/0.txt": "great great movie",
            "aclImdb/train/neg/0.txt": "bad movie",
            "aclImdb/test/pos/0.txt": "great fun",
        })
        tr = Imdb(data_file=f, mode="train", cutoff=0)
        assert len(tr) == 2
        labels = sorted(int(tr[i][1]) for i in range(2))
        assert labels == [0, 1]  # pos=0, neg=1
        # frequency-sorted: 'great'(3 incl. test split) first
        assert tr.word_idx["great"] < tr.word_idx["bad"]
        te = Imdb(data_file=f, mode="test", cutoff=0)
        assert len(te) == 1 and int(te[0][1]) == 0
        # ONE vocab across splits (reference build_dict): ids align
        assert te.word_idx == tr.word_idx
        # './'-prefixed tar members parse too
        f2 = _tar_with(tmp_path, "b.tar.gz", {
            "./aclImdb/train/pos/0.txt": "nice movie",
        })
        assert len(Imdb(data_file=f2, mode="train", cutoff=0)) == 1

    def test_cutoff_is_frequency_threshold(self, tmp_path):
        f = _tar_with(tmp_path, "a.tar.gz", {
            "aclImdb/train/pos/0.txt": "alpha alpha alpha beta beta gamma",
        })
        # keep words with freq > 1 (reference semantics), not top-1
        ds = Imdb(data_file=f, mode="train", cutoff=1)
        assert set(ds.word_idx) == {"alpha", "beta", "<unk>"}
        unk = ds.word_idx["<unk>"]
        assert (ds[0][0] == unk).sum() == 1  # gamma -> <unk>

    def test_bad_mode_rejected(self, tmp_path):
        f = _tar_with(tmp_path, "a.tar.gz", {
            "aclImdb/train/pos/0.txt": "x",
        })
        with pytest.raises(ValueError, match="mode"):
            Imdb(data_file=f, mode="dev")


class TestImikolov:
    def _tar(self, tmp_path):
        return _tar_with(tmp_path, "ptb.tar.gz", {
            "simple-examples/data/ptb.train.txt": "a b c\na b",
            "simple-examples/data/ptb.valid.txt": "c b a",
        })

    def test_ngram_windows(self, tmp_path):
        ds = Imikolov(data_file=self._tar(tmp_path), data_type="NGRAM",
                      window_size=3, mode="train")
        # line1: <s> a b c <e> -> 3 windows; line2: <s> a b <e> -> 2
        assert len(ds) == 5
        s, e = ds.word_idx["<s>"], ds.word_idx["<e>"]
        assert ds[0][0] == s and ds[2][-1] == e

    def test_reference_defaults(self, tmp_path):
        """Reference imikolov defaults: window_size=-1, min_word_freq=50
        (ADVICE r3).  NGRAM with the -1 default must fail loudly; the
        freq-50 default prunes a tiny vocab to the specials."""
        import inspect
        sig = inspect.signature(Imikolov.__init__)
        assert sig.parameters["window_size"].default == -1
        assert sig.parameters["min_word_freq"].default == 50
        tar = self._tar(tmp_path)
        with pytest.raises(ValueError, match="window_size"):
            Imikolov(data_file=tar, data_type="NGRAM")
        ds = Imikolov(data_file=tar, data_type="SEQ")
        assert set(ds.word_idx) == {"<unk>", "<s>", "<e>"}

    def test_seq_mode_and_valid_split(self, tmp_path):
        tar = self._tar(tmp_path)
        ds = Imikolov(data_file=tar, data_type="SEQ", mode="valid")
        assert len(ds) == 1
        ids = ds[0]
        assert ids[0] == ds.word_idx["<s>"] and ids[-1] == ds.word_idx["<e>"]
        assert len(ids) == 5
        # vocab comes from the TRAIN split in both modes -> ids align
        tr = Imikolov(data_file=tar, data_type="SEQ", mode="train")
        assert tr.word_idx == ds.word_idx


class TestMovielens:
    def test_zip_parse(self, tmp_path):
        z = tmp_path / "ml-1m.zip"
        with zipfile.ZipFile(z, "w") as zf:
            zf.writestr("ml-1m/users.dat", "1::M::25::4::00000\n"
                                           "2::F::35::7::11111\n")
            zf.writestr("ml-1m/movies.dat",
                        "10::Toy Story (1995)::Animation|Comedy\n"
                        "20::Heat (1995)::Action\n")
            zf.writestr("ml-1m/ratings.dat",
                        "1::10::5::978300760\n2::20::3::978302109\n")
        ds = Movielens(data_file=str(z), mode="train", test_ratio=0.0)
        assert len(ds) == 2
        uid, gender, age, job, mid, cats, title, rating = ds[0]
        assert int(uid) == 1 and int(gender) == 0 and int(mid) == 10
        assert cats.sum() == 2  # Animation + Comedy multi-hot
        assert float(rating) == 5.0
        assert len(ds.categories_dict) == 3

    def test_dir_layout_too(self, tmp_path):
        d = tmp_path / "ml"
        d.mkdir()
        (d / "users.dat").write_text("1::M::25::4::0\n")
        (d / "movies.dat").write_text("5::Alien (1979)::Horror\n")
        (d / "ratings.dat").write_text("1::5::4::1\n")
        ds = Movielens(data_file=str(d), test_ratio=0.0)
        assert len(ds) == 1


class TestWMT:
    def test_wmt14_pairs_and_dicts(self, tmp_path):
        f = _tar_with(tmp_path, "wmt14.tar.gz", {
            "train/part-00": "le chat\tthe cat\nle chien\tthe dog",
            "test/part-00": "le chat\tthe cat",
        })
        ds = WMT14(data_file=f, mode="train", dict_size=30)
        assert len(ds) == 2
        src, trg_in, trg_out = ds[0]
        assert trg_in[0] == ds.trg_ids["<s>"]
        assert trg_out[-1] == ds.trg_ids["<e>"]
        assert len(trg_in) == len(trg_out)
        # reserved ids first
        assert ds.src_ids["<s>"] == 0 and ds.src_ids["<unk>"] == 2
        rev = ds.get_dict("src", reverse=True)
        assert rev[ds.src_ids["le"]] == "le"
        # bare boolean positional = the reference's reverse flag (src)
        assert ds.get_dict(False) is ds.src_ids
        assert ds.get_dict(True)[ds.src_ids["le"]] == "le"

    def test_wmt16_get_dict_respects_source_lang(self, tmp_path):
        """get_dict('de') on a lang='de' dataset must return the GERMAN
        dict (review finding: language selection was inverted)."""
        f = _tar_with(tmp_path, "w16.tar.gz", {
            "wmt16/train.en": "the cat",
            "wmt16/train.de": "die katze",
        })
        de = WMT16(data_file=f, mode="train", lang="de")
        assert "die" in de.get_dict("de")      # German words
        assert "the" in de.get_dict("en")      # English words
        assert de.get_dict("de") is de.src_ids

    def test_wmt14_bad_mode_rejected(self, tmp_path):
        f = _tar_with(tmp_path, "w14.tar.gz", {
            "train/p": "a	b",
        })
        with pytest.raises(ValueError, match="train/test/gen"):
            WMT14(data_file=f, mode="valid")

    def test_wmt16_bad_mode_and_lang_rejected(self, tmp_path):
        f = _tar_with(tmp_path, "w16c.tar.gz", {
            "wmt16/train.en": "a", "wmt16/train.de": "b",
        })
        with pytest.raises(ValueError, match="mode"):
            WMT16(data_file=f, mode="gen")
        ds = WMT16(data_file=f, mode="train", lang="de")
        with pytest.raises(ValueError, match="language"):
            ds.get_dict("deu")

    def test_conll_ragged_props_rejected(self, tmp_path):
        words = tmp_path / "w.txt"
        props = tmp_path / "p.txt"
        words.write_text("A\nB\n")
        props.write_text("-\t(A0*\nsat\n")   # second row short
        with pytest.raises(ValueError, match="ragged"):
            Conll05st(words_file=str(words), props_file=str(props))

    def test_wmt16_misaligned_corpus_rejected(self, tmp_path):
        f = _tar_with(tmp_path, "w16b.tar.gz", {
            "wmt16/train.en": "a\nb",
            "wmt16/train.de": "x",
        })
        with pytest.raises(RuntimeError, match="misaligned"):
            WMT16(data_file=f, mode="train")

    def test_wmt16_lang_sides(self, tmp_path):
        f = _tar_with(tmp_path, "wmt16.tar.gz", {
            "wmt16/train.en": "the cat\nthe dog",
            "wmt16/train.de": "die katze\nder hund",
            "wmt16/val.en": "a cat",
            "wmt16/val.de": "eine katze",
        })
        en = WMT16(data_file=f, mode="train", lang="en")
        assert "the" in en.src_ids and "die" in en.trg_ids
        de = WMT16(data_file=f, mode="val", lang="de")
        assert "eine" in de.src_ids and "a" in de.trg_ids
        assert len(de) == 1


class TestConll05st:
    def test_spans_to_bio_and_samples(self, tmp_path):
        words = tmp_path / "words.txt"
        props = tmp_path / "props.txt"
        words.write_text("The\ncat\nsat\n\nDogs\nbark\n")
        # sentence 1: predicate 'sat' with A0 span over 'The cat'
        props.write_text(
            "-\t(A0*\n-\t*)\nsat\t(V*)\n\n-\t(A0*)\nbark\t(V*)\n")
        ds = Conll05st(words_file=str(words), props_file=str(props))
        assert len(ds) == 2
        w_ids, pred, labels = ds[0]
        assert len(w_ids) == 3 and len(labels) == 3
        rev = {i: t for t, i in ds.label_dict.items()}
        assert [rev[int(l)] for l in labels] == ["B-A0", "I-A0", "B-V"]
        assert int(pred) == ds.word_dict["sat"]
        w2, p2, l2 = ds[1]
        rev2 = [rev[int(l)] for l in l2]
        assert rev2 == ["B-A0", "B-V"]


# ----------------------------------------------------------------- audio

class TestLoadWav:
    def test_pcm16_roundtrip(self, tmp_path):
        t = np.linspace(0, 1, 1600, endpoint=False)
        sig = 0.5 * np.sin(2 * np.pi * 440 * t)
        p = tmp_path / "a.wav"
        _write_wav(p, sig)
        x, sr = load_wav(str(p))
        assert sr == 16000 and x.shape == (1600,)
        np.testing.assert_allclose(x, sig, atol=1e-3)


class TestTESS:
    def _make(self, tmp_path):
        d = tmp_path / "TESS"
        emotions = ["angry", "happy", "sad"]
        for e in emotions:
            sub = d / f"OAF_{e}"
            sub.mkdir(parents=True)
            for w in ("back", "bar", "base", "bean"):
                _write_wav(sub / f"OAF_{w}_{e}.wav",
                           np.random.default_rng(0).normal(size=800) * 0.1)
        return str(d)

    def test_split_and_labels(self, tmp_path):
        d = self._make(tmp_path)
        tr = TESS(mode="train", n_folds=4, split=1, archive_dir=d)
        dv = TESS(mode="dev", n_folds=4, split=1, archive_dir=d)
        assert len(tr) + len(dv) == 12
        assert len(dv) == 3
        assert tr.emotions == ["angry", "happy", "sad"]
        wav, lab = tr[0]
        assert wav.ndim == 1 and 0 <= int(lab) < 3

    def test_mfcc_feature(self, tmp_path):
        d = self._make(tmp_path)
        ds = TESS(mode="dev", n_folds=4, split=2, archive_dir=d,
                  feature_type="mfcc", n_mfcc=13, n_fft=256)
        feat, lab = ds[0]
        assert feat.shape[0] == 13

    def test_bad_split_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="split"):
            TESS(split=9, n_folds=5, archive_dir=self._make(tmp_path))

    def test_guidance(self):
        with pytest.raises(RuntimeError, match="archive"):
            TESS()


class TestESC50:
    def test_fold_split(self, tmp_path):
        d = tmp_path / "esc"
        (d / "audio").mkdir(parents=True)
        (d / "meta").mkdir()
        rows = ["filename,fold,target,category"]
        for i in range(6):
            fn = f"clip{i}.wav"
            _write_wav(d / "audio" / fn,
                       np.random.default_rng(i).normal(size=400) * 0.1)
            rows.append(f"{fn},{i % 3 + 1},{i % 2},cls")
        (d / "meta" / "esc50.csv").write_text("\n".join(rows) + "\n")
        tr = ESC50(mode="train", split=1, archive_dir=str(d))
        dv = ESC50(mode="dev", split=1, archive_dir=str(d))
        assert len(tr) == 4 and len(dv) == 2
        wav, lab = dv[0]
        assert wav.shape == (400,) and int(lab) in (0, 1)

    def test_bad_split_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="split"):
            ESC50(split=99, archive_dir="/nonexistent")

    def test_bad_mode_rejected(self, tmp_path):
        with pytest.raises(ValueError, match="mode"):
            ESC50(mode="test", archive_dir="/nonexistent")
        with pytest.raises(ValueError, match="mode"):
            TESS(mode="test", archive_dir="/nonexistent")

    def test_spectrogram_feature(self, tmp_path):
        d = tmp_path / "esc"
        (d / "audio").mkdir(parents=True)
        (d / "meta").mkdir()
        _write_wav(d / "audio" / "c.wav",
                   np.random.default_rng(0).normal(size=1024) * 0.1)
        (d / "meta" / "esc50.csv").write_text(
            "filename,fold,target\nc.wav,1,3\n")
        ds = ESC50(mode="dev", split=1, archive_dir=str(d),
                   feature_type="spectrogram", n_fft=256)
        feat, lab = ds[0]
        assert feat.shape[0] == 129 and int(lab) == 3  # n_fft//2+1 bins
