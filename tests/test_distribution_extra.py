"""Oracle tests for the round-3 distribution/transform additions.

Reference: python/paddle/distribution/ — binomial.py, cauchy.py, chi2.py,
continuous_bernoulli.py, independent.py, multivariate_normal.py,
transform.py.  Oracles: scipy.stats and torch.distributions (the same
strategy as tests/test_distribution.py).
"""

import numpy as np
import pytest
import scipy.stats as st
import torch

import paddle_tpu.distribution as D


class TestBinomial:
    def test_log_prob_vs_scipy(self):
        d = D.Binomial(10, 0.3)
        ks = np.arange(0, 11, dtype=np.float32)
        np.testing.assert_allclose(np.asarray(d.log_prob(ks)),
                                   st.binom.logpmf(ks, 10, 0.3),
                                   rtol=1e-5, atol=1e-6)

    def test_moments(self):
        d = D.Binomial(7, np.array([0.2, 0.8], np.float32))
        np.testing.assert_allclose(np.asarray(d.mean), [1.4, 5.6], rtol=1e-6)
        np.testing.assert_allclose(np.asarray(d.variance),
                                   7 * np.array([0.2 * 0.8, 0.8 * 0.2]),
                                   rtol=1e-5)

    def test_entropy_vs_scipy(self):
        d = D.Binomial(12, 0.35)
        np.testing.assert_allclose(float(d.entropy()),
                                   st.binom.entropy(12, 0.35), rtol=1e-5)

    def test_sample_mean(self):
        import jax
        d = D.Binomial(20, 0.4)
        s = d.sample((4000,), key=jax.random.PRNGKey(0))
        assert abs(float(s.mean()) - 8.0) < 0.25
        assert float(s.max()) <= 20 and float(s.min()) >= 0


class TestCauchy:
    def test_log_prob_and_cdf_vs_scipy(self):
        d = D.Cauchy(1.5, 2.0)
        xs = np.linspace(-8, 8, 23).astype(np.float32)
        np.testing.assert_allclose(np.asarray(d.log_prob(xs)),
                                   st.cauchy.logpdf(xs, 1.5, 2.0), rtol=1e-5)
        np.testing.assert_allclose(np.asarray(d.cdf(xs)),
                                   st.cauchy.cdf(xs, 1.5, 2.0), rtol=1e-5)

    def test_entropy_vs_scipy(self):
        np.testing.assert_allclose(float(D.Cauchy(0.0, 3.0).entropy()),
                                   st.cauchy.entropy(0.0, 3.0), rtol=1e-6)

    def test_sample_median(self):
        import jax
        s = D.Cauchy(2.0, 1.0).sample((8001,), key=jax.random.PRNGKey(1))
        assert abs(float(np.median(np.asarray(s))) - 2.0) < 0.1


class TestChi2:
    def test_log_prob_vs_scipy(self):
        d = D.Chi2(5.0)
        xs = np.linspace(0.2, 12, 15).astype(np.float32)
        np.testing.assert_allclose(np.asarray(d.log_prob(xs)),
                                   st.chi2.logpdf(xs, 5), rtol=1e-4)

    def test_mean_via_gamma(self):
        d = D.Chi2(8.0)
        np.testing.assert_allclose(float(d.mean), 8.0, rtol=1e-6)


class TestContinuousBernoulli:
    def test_log_prob_vs_torch(self):
        for p in (0.2, 0.5, 0.77):
            d = D.ContinuousBernoulli(p)
            t = torch.distributions.ContinuousBernoulli(probs=torch.tensor(p))
            xs = np.linspace(0.01, 0.99, 17).astype(np.float32)
            np.testing.assert_allclose(
                np.asarray(d.log_prob(xs)),
                t.log_prob(torch.tensor(xs)).numpy(), rtol=2e-4, atol=2e-4)

    def test_mean_vs_torch(self):
        for p in (0.15, 0.5, 0.9):
            d = D.ContinuousBernoulli(p)
            t = torch.distributions.ContinuousBernoulli(probs=torch.tensor(p))
            np.testing.assert_allclose(float(d.mean), float(t.mean),
                                       rtol=1e-4, atol=1e-4)

    def test_cdf_matches_sampling(self):
        import jax
        d = D.ContinuousBernoulli(0.3)
        s = np.asarray(d.sample((6000,), key=jax.random.PRNGKey(2)))
        for q in (0.25, 0.5, 0.75):
            emp = (s <= q).mean()
            np.testing.assert_allclose(emp, float(d.cdf(q)), atol=0.02)


class TestIndependent:
    def test_log_prob_sums_event_dims(self):
        base = D.Normal(np.zeros((3, 4), np.float32),
                        np.ones((3, 4), np.float32))
        ind = D.Independent(base, 1)
        x = np.random.default_rng(0).normal(size=(3, 4)).astype(np.float32)
        np.testing.assert_allclose(np.asarray(ind.log_prob(x)),
                                   np.asarray(base.log_prob(x)).sum(-1),
                                   rtol=1e-6)

    def test_vs_torch(self):
        rng = np.random.default_rng(1)
        loc = rng.normal(size=(2, 3)).astype(np.float32)
        x = rng.normal(size=(2, 3)).astype(np.float32)
        ours = D.Independent(D.Normal(loc, np.ones_like(loc)), 1)
        theirs = torch.distributions.Independent(
            torch.distributions.Normal(torch.tensor(loc), 1.0), 1)
        np.testing.assert_allclose(np.asarray(ours.log_prob(x)),
                                   theirs.log_prob(torch.tensor(x)).numpy(),
                                   rtol=1e-5)


class TestMultivariateNormal:
    def _cov(self, rng, d=3):
        a = rng.normal(size=(d, d))
        return (a @ a.T + d * np.eye(d)).astype(np.float32)

    def test_log_prob_vs_scipy(self):
        rng = np.random.default_rng(2)
        cov = self._cov(rng)
        loc = rng.normal(size=3).astype(np.float32)
        d = D.MultivariateNormal(loc, covariance_matrix=cov)
        xs = rng.normal(size=(5, 3)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(d.log_prob(xs)),
            st.multivariate_normal.logpdf(xs, loc, cov), rtol=2e-4)

    def test_entropy_vs_scipy(self):
        rng = np.random.default_rng(3)
        cov = self._cov(rng)
        d = D.MultivariateNormal(np.zeros(3, np.float32),
                                 covariance_matrix=cov)
        np.testing.assert_allclose(float(d.entropy()),
                                   st.multivariate_normal.entropy(None, cov),
                                   rtol=1e-5)

    def test_scale_tril_and_sampling(self):
        import jax
        rng = np.random.default_rng(4)
        cov = self._cov(rng)
        tril = np.linalg.cholesky(cov)
        d = D.MultivariateNormal(np.zeros(3, np.float32), scale_tril=tril)
        np.testing.assert_allclose(np.asarray(d.covariance_matrix), cov,
                                   rtol=1e-5)
        s = np.asarray(d.sample((20000,), key=jax.random.PRNGKey(3)))
        np.testing.assert_allclose(np.cov(s.T), cov, rtol=0.15, atol=0.3)


class TestTransforms:
    def _roundtrip(self, t, xs, torch_t=None):
        ys = np.asarray(t.forward(xs))
        back = np.asarray(t.inverse(ys))
        np.testing.assert_allclose(back, xs, rtol=1e-4, atol=1e-5)
        if torch_t is not None:
            np.testing.assert_allclose(
                ys, torch_t(torch.tensor(xs)).numpy(), rtol=1e-5, atol=1e-6)
            np.testing.assert_allclose(
                np.asarray(t.forward_log_det_jacobian(xs)),
                torch_t.log_abs_det_jacobian(
                    torch.tensor(xs), torch_t(torch.tensor(xs))).numpy(),
                rtol=1e-4, atol=1e-5)

    def test_exp_power_sigmoid_tanh_vs_torch(self):
        xs = np.linspace(-2, 2, 9).astype(np.float32)
        self._roundtrip(D.ExpTransform(), xs,
                        torch.distributions.transforms.ExpTransform())
        self._roundtrip(D.SigmoidTransform(), xs,
                        torch.distributions.transforms.SigmoidTransform())
        self._roundtrip(D.TanhTransform(), xs * 0.9,
                        torch.distributions.transforms.TanhTransform())
        pos = np.linspace(0.3, 3, 9).astype(np.float32)
        self._roundtrip(D.PowerTransform(2.0), pos,
                        torch.distributions.transforms.PowerTransform(
                            torch.tensor(2.0)))

    def test_chain(self):
        xs = np.linspace(-1, 1, 7).astype(np.float32)
        chain = D.ChainTransform([D.ExpTransform(),
                                  D.PowerTransform(2.0)])
        np.testing.assert_allclose(np.asarray(chain.forward(xs)),
                                   np.exp(xs) ** 2, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(chain.inverse(
            chain.forward(xs))), xs, rtol=1e-4, atol=1e-5)
        tc = torch.distributions.transforms.ComposeTransform(
            [torch.distributions.transforms.ExpTransform(),
             torch.distributions.transforms.PowerTransform(torch.tensor(2.0))])
        np.testing.assert_allclose(
            np.asarray(chain.forward_log_det_jacobian(xs)),
            tc.log_abs_det_jacobian(torch.tensor(xs),
                                    tc(torch.tensor(xs))).numpy(),
            rtol=1e-4)

    def test_stick_breaking_vs_torch(self):
        rng = np.random.default_rng(5)
        xs = rng.normal(size=(4, 3)).astype(np.float32)
        t = D.StickBreakingTransform()
        tt = torch.distributions.transforms.StickBreakingTransform()
        ys = np.asarray(t.forward(xs))
        np.testing.assert_allclose(ys, tt(torch.tensor(xs)).numpy(),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(ys.sum(-1), 1.0, rtol=1e-5)
        np.testing.assert_allclose(np.asarray(t.inverse(ys)), xs,
                                   rtol=1e-3, atol=1e-4)
        np.testing.assert_allclose(
            np.asarray(t.forward_log_det_jacobian(xs)),
            tt.log_abs_det_jacobian(torch.tensor(xs),
                                    tt(torch.tensor(xs))).numpy(),
            rtol=1e-4, atol=1e-5)

    def test_independent_and_reshape_and_stack(self):
        rng = np.random.default_rng(6)
        xs = rng.normal(size=(2, 6)).astype(np.float32)
        it = D.IndependentTransform(D.ExpTransform(), 1)
        np.testing.assert_allclose(np.asarray(it.forward_log_det_jacobian(xs)),
                                   xs.sum(-1), rtol=1e-5)
        rt = D.ReshapeTransform((6,), (2, 3))
        assert rt.forward(xs).shape == (2, 2, 3)
        np.testing.assert_allclose(np.asarray(rt.inverse(rt.forward(xs))), xs)
        with pytest.raises(ValueError):
            D.ReshapeTransform((6,), (4, 2))
        stk = D.StackTransform([D.ExpTransform(), D.AbsTransform()], axis=0)
        ys = np.asarray(stk.forward(xs))
        np.testing.assert_allclose(ys[0], np.exp(xs[0]), rtol=1e-5)
        np.testing.assert_allclose(ys[1], np.abs(xs[1]), rtol=1e-5)

    def test_softmax_transform(self):
        xs = np.random.default_rng(7).normal(size=(3, 4)).astype(np.float32)
        t = D.SoftmaxTransform()
        ys = np.asarray(t.forward(xs))
        np.testing.assert_allclose(ys.sum(-1), 1.0, rtol=1e-5)
        with pytest.raises(NotImplementedError):
            t.forward_log_det_jacobian(xs)

    def test_transformed_distribution_with_new_transforms(self):
        """log N(x;0,1) through exp = lognormal density (reference
        TransformedDistribution composition check)."""
        base = D.Normal(0.0, 1.0)
        logn = D.TransformedDistribution(base, [D.ExpTransform()])
        xs = np.linspace(0.2, 4, 9).astype(np.float32)
        np.testing.assert_allclose(np.asarray(logn.log_prob(xs)),
                                   st.lognorm.logpdf(xs, 1.0), rtol=1e-4)
