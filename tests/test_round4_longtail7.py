"""Round-4 seventh sweep: affine/perspective/erase/adjust_gamma
transforms (+Random* classes), the image-backend trio, ReduceType.

Oracles: identity-parameter warps must reproduce the input exactly;
pure-translation affine against np.roll; perspective corner mapping;
PIL roundtrip for image_load.
"""

import os
import tempfile

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.vision as vision
import paddle_tpu.vision.transforms as T


def _img(h=8, w=10, c=3, seed=0):
    return np.random.RandomState(seed).randint(
        0, 255, (h, w, c)).astype("uint8")


class TestAffine:
    def test_identity(self):
        img = _img()
        out = T.affine(img, angle=0.0)
        np.testing.assert_array_equal(out, img)

    def test_pure_translation_matches_roll(self):
        img = _img()
        out = T.affine(img, angle=0.0, translate=(2, 1), fill=0)
        # shifted content: out[y+1, x+2] == img[y, x] inside bounds
        np.testing.assert_array_equal(out[1:, 2:], img[:-1, :-2])
        assert (out[0] == 0).all() and (out[:, :2] == 0).all()

    def test_rotation_matches_rotate(self):
        img = _img()
        np.testing.assert_array_equal(
            T.affine(img, angle=90.0), T.rotate(img, 90.0))

    def test_scale_about_center(self):
        img = np.zeros((9, 9), "uint8")
        img[4, 4] = 255
        out = T.affine(img, angle=0.0, scale=2.0)
        assert out[4, 4] == 255      # center fixed point


class TestPerspective:
    def test_identity_corners(self):
        img = _img()
        pts = [[0, 0], [9, 0], [9, 7], [0, 7]]
        out = T.perspective(img, pts, pts)
        np.testing.assert_array_equal(out, img)

    def test_translation_homography(self):
        img = _img()
        start = [[0, 0], [9, 0], [9, 7], [0, 7]]
        end = [[1, 0], [10, 0], [10, 7], [1, 7]]   # shift right by 1
        out = T.perspective(img, start, end)
        np.testing.assert_array_equal(out[:, 1:], img[:, :-1])


class TestEraseGamma:
    def test_erase_region_and_inplace(self):
        img = _img()
        out = T.erase(img, 2, 3, 4, 5, 7)
        assert (out[2:6, 3:8] == 7).all()
        assert (img[2:6, 3:8] != 7).any()          # original untouched
        T.erase(img, 0, 0, 2, 2, 9, inplace=True)
        assert (img[:2, :2] == 9).all()

    def test_adjust_gamma(self):
        img = _img()
        out = T.adjust_gamma(img, 1.0)
        np.testing.assert_allclose(out, img, atol=1)
        dark = T.adjust_gamma(img, 2.0)
        assert dark.mean() < img.mean()
        with pytest.raises(ValueError):
            T.adjust_gamma(img, -1.0)

    def test_random_classes_shapes(self):
        img = _img()
        assert T.RandomErasing(prob=1.0)(img).shape == img.shape
        assert T.RandomErasing(prob=0.0)(img) is not None
        assert T.RandomAffine(10, translate=(0.1, 0.1), scale=(0.9, 1.1),
                              shear=5)(img).shape == img.shape
        assert T.RandomPerspective(prob=1.0)(img).shape == img.shape
        with pytest.raises(ValueError):
            T.RandomErasing(prob=2.0)


class TestImageBackend:
    def test_get_set_and_load(self):
        assert vision.get_image_backend() == "pil"
        with pytest.raises(ValueError):
            vision.set_image_backend("nope")
        with pytest.raises(ImportError):
            vision.set_image_backend("cv2")
        from PIL import Image
        img = _img()
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "t.png")
            Image.fromarray(img).save(path)
            loaded = vision.image_load(path)
            np.testing.assert_array_equal(np.asarray(loaded), img)
            arr = vision.image_load(path, backend="tensor")
            assert isinstance(arr, np.ndarray) and arr.shape == img.shape
        vision.set_image_backend("pil")


class TestReduceType:
    def test_enum_values(self):
        rt = paddle.distributed.ReduceType
        assert rt.kRedSum == 0
        assert rt.kRedAvg == 4
        assert len({rt.kRedSum, rt.kRedMax, rt.kRedMin, rt.kRedProd,
                    rt.kRedAvg, rt.kRedAny, rt.kRedAll}) == 7


class TestReviewRegressions7:
    def test_zero_distortion_is_identity(self):
        img = _img()
        out = T.RandomPerspective(prob=1.0, distortion_scale=0.0)(img)
        np.testing.assert_array_equal(out, img)

    def test_sequence_fill(self):
        img = _img()
        out = T.affine(img, angle=0.0, translate=(3, 0),
                       fill=(255, 0, 0))
        # vacated left columns take the per-channel fill
        assert (out[:, :3, 0] == 255).all()
        assert (out[:, :3, 1] == 0).all()
        # rotate inherits through the shared kernel
        out2 = T.rotate(img, 45.0, fill=7)
        assert out2.shape == img.shape

    def test_erase_inplace_readonly_guarded(self):
        ro = _img()
        ro.setflags(write=False)
        with pytest.raises(ValueError, match="writable"):
            T.erase(ro, 0, 0, 2, 2, 5, inplace=True)

    def test_random_value_uint8_in_range(self):
        img = _img(16, 16)
        out = T.RandomErasing(prob=1.0, scale=(0.2, 0.4),
                              value="random")(img)
        diff = out != img
        assert diff.any()
        # uint8 noise spans the range without wraparound artifacts of a
        # float->uint8 C-cast (which lands almost everything at 0/255)
        vals = out[diff.any(-1)]
        assert vals.std() > 20

    def test_image_load_rejects_bad_backend(self):
        with pytest.raises(ValueError):
            vision.image_load("nope.png", backend="bogus")


class TestWaveEight:
    def test_as_tensor_and_where_(self):
        import jax.numpy as jnp
        t = paddle.as_tensor([1.0, 2.0], dtype="float32")
        assert t.dtype == jnp.float32
        out = paddle.where_(jnp.asarray([True, False]),
                            jnp.asarray([1.0, 1.0]),
                            jnp.asarray([2.0, 2.0]))
        np.testing.assert_allclose(np.asarray(out), [1.0, 2.0])

    def test_inplace_named_activations(self):
        import jax.numpy as jnp
        import paddle_tpu.nn.functional as F
        x = jnp.asarray([-1.0, 1.0])
        np.testing.assert_allclose(np.asarray(F.elu_(x)),
                                   np.asarray(F.elu(x)))
        np.testing.assert_allclose(np.asarray(F.leaky_relu_(x)),
                                   np.asarray(F.leaky_relu(x)))

    def test_f_diag_embed(self):
        import jax.numpy as jnp
        import paddle_tpu.nn.functional as F
        out = F.diag_embed(jnp.asarray([1.0, 2.0]))
        np.testing.assert_allclose(np.asarray(out), [[1, 0], [0, 2]])

    def test_device_type_listings(self):
        types = paddle.device.get_all_device_type()
        assert "cpu" in types
        assert isinstance(paddle.device.get_all_custom_device_type(), list)

    def test_random_erasing_validates_value(self):
        with pytest.raises(ValueError, match="random"):
            T.RandomErasing(value="randm")
        # array values work (per-channel fill, no ambiguous-truth crash)
        img = _img()
        out = T.RandomErasing(prob=1.0, scale=(0.2, 0.4),
                              value=np.asarray([1, 2, 3]))(img)
        assert out.shape == img.shape
