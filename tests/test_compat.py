"""Porting shims: paddle-style methods on jax arrays (opt-in)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu
from paddle_tpu.compat import enable_tensor_methods


def test_tensor_methods_after_enable():
    enable_tensor_methods()
    enable_tensor_methods()          # idempotent
    x = jnp.asarray([[1.0, -2.0], [3.0, 4.0]])
    np.testing.assert_allclose(x.numpy(), np.asarray(x))
    assert x.numel() == 4 and x.dim() == 2
    np.testing.assert_allclose(np.asarray(x.abs()), np.abs(np.asarray(x)))
    np.testing.assert_allclose(np.asarray(x.add(1.0)), np.asarray(x) + 1)
    np.testing.assert_allclose(np.asarray(x.t()), np.asarray(x).T)
    np.testing.assert_allclose(np.asarray(x.scale(2.0, 1.0)),
                               np.asarray(x) * 2 + 1)
    assert x.unsqueeze(0).shape == (1, 2, 2)
    # detach blocks gradients
    g = jax.grad(lambda a: jnp.sum(a.detach() * a))(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(x))


def test_numpy_method_raises_under_trace():
    enable_tensor_methods()

    @jax.jit
    def f(a):
        a.numpy()                    # eager-only: must fail loudly
        return a

    with pytest.raises((AttributeError, jax.errors.TracerArrayConversionError,
                        jax.errors.ConcretizationTypeError)):
        f(jnp.ones(3))
