"""Round-4 sixth sweep: functional quasi-Newton minimizers, static
Print/py_func/WeightNormParamAttr/ExponentialMovingAverage,
linalg.lu_solve, Tensor.apply, saved_tensors_hooks,
incubate.multiprocessing.

Oracles: scipy (erf + derivative through py_func's custom vjp), direct
solve residuals for lu_solve, closed-form quadratic minima.
"""

import warnings

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import scipy.special as sp

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.initializer as I
from paddle_tpu.incubate.optimizer import functional as fopt


class TestFunctionalMinimizers:
    def _quad(self):
        return lambda x: (x[0] - 1.0) ** 2 + 2.0 * (x[1] - 2.0) ** 2

    @pytest.mark.parametrize("minimize", [fopt.minimize_lbfgs,
                                          fopt.minimize_bfgs],
                             ids=["lbfgs", "bfgs"])
    def test_quadratic_minimum(self, minimize):
        conv, ncalls, pos, val, grad = minimize(self._quad(),
                                                jnp.asarray([0.0, 0.0]))
        assert bool(conv)
        np.testing.assert_allclose(np.asarray(pos), [1.0, 2.0], atol=1e-4)
        assert float(val) == pytest.approx(0.0, abs=1e-6)
        np.testing.assert_allclose(np.asarray(grad), 0.0, atol=1e-3)
        assert int(ncalls) >= 1

    def test_logcosh_nonquadratic_lbfgs(self):
        # smooth strongly-convex non-quadratic, min at (1, 2)
        f = lambda x: (jnp.logaddexp(x[0] - 1.0, -(x[0] - 1.0))
                       + jnp.logaddexp(2.0 * (x[1] - 2.0),
                                       -2.0 * (x[1] - 2.0)))
        conv, _, pos, val, _ = fopt.minimize_lbfgs(
            f, jnp.asarray([-1.2, 4.0]), max_iters=100,
            tolerance_grad=1e-5)
        np.testing.assert_allclose(np.asarray(pos), [1.0, 2.0], atol=1e-3)

    def test_lbfgs_rejects_dense_h0(self):
        with pytest.raises(NotImplementedError):
            fopt.minimize_lbfgs(self._quad(), jnp.zeros(2),
                                initial_inverse_hessian_estimate=jnp.eye(2))


class TestStaticExtras:
    def test_print_message_with_braces(self, capfd):
        out = paddle.static.Print(jnp.ones(2), message="step {i} {}")
        jax.effects_barrier()
        assert float(out[0]) == 1.0
        captured = capfd.readouterr()
        assert "step {i} {}" in (captured.out + captured.err)

    def test_print_is_identity_under_jit(self, capfd):
        f = jax.jit(lambda x: paddle.static.Print(x, message="dbg") * 2)
        out = f(jnp.ones(3))
        jax.effects_barrier()
        assert float(out[0]) == 2.0
        captured = capfd.readouterr()
        assert "dbg" in captured.out or "dbg" in captured.err

    def test_py_func_forward_and_custom_vjp(self):
        def host_fn(x):
            return sp.erf(x)

        # the REFERENCE backward contract: (inputs..., outputs..., grads)
        def host_bwd(x, out, g):
            assert np.allclose(np.asarray(out), sp.erf(np.asarray(x)))
            return g * 2.0 / np.sqrt(np.pi) * np.exp(-np.asarray(x) ** 2)

        x = jnp.asarray([0.3, -0.7])
        y = paddle.static.py_func(host_fn, x, out=jnp.zeros(2))
        np.testing.assert_allclose(np.asarray(y), sp.erf(np.asarray(x)),
                                   rtol=1e-6)
        lossg = jax.grad(lambda x: paddle.static.py_func(
            host_fn, x, out=jnp.zeros(2), backward_func=host_bwd).sum())
        want = 2 / np.sqrt(np.pi) * np.exp(-np.asarray(x) ** 2)
        np.testing.assert_allclose(np.asarray(lossg(x)), want, rtol=1e-5)
        # the same op inside jit (pure_callback's whole point)
        np.testing.assert_allclose(np.asarray(jax.jit(lossg)(x)), want,
                                   rtol=1e-5)

    def test_py_func_skip_vars_in_backward_input(self):
        out_t = jnp.zeros(2)

        def host_bwd(x, g):     # out skipped -> (inputs..., grads)
            return g * np.cos(np.asarray(x))

        x = jnp.asarray([0.2, 1.1])
        g = jax.grad(lambda x: paddle.static.py_func(
            lambda a: np.sin(a), x, out=out_t, backward_func=host_bwd,
            skip_vars_in_backward_input=[out_t]).sum())(x)
        np.testing.assert_allclose(np.asarray(g), np.cos(np.asarray(x)),
                                   rtol=1e-5)

    def test_py_func_multi_output(self):
        outs = paddle.static.py_func(
            lambda a: (np.asarray(a) + 1, np.asarray(a) * 2),
            jnp.ones(3), out=[jnp.zeros(3), jnp.zeros(3)])
        assert isinstance(outs, list) and len(outs) == 2
        np.testing.assert_allclose(np.asarray(outs[0]), 2.0)
        np.testing.assert_allclose(np.asarray(outs[1]), 2.0)

    def test_weight_norm_param_attr(self):
        with warnings.catch_warnings(record=True):
            warnings.simplefilter("always")
            attr = paddle.static.WeightNormParamAttr(
                dim=0, initializer=I.Constant(0.3))
        lin = nn.Linear(3, 4, weight_attr=attr)
        assert float(lin.weight[0, 0]) == pytest.approx(0.3)
        assert attr.dim == 0

    def test_ema_update_and_apply(self):
        ema = paddle.static.ExponentialMovingAverage(0.5)
        ema.update({"w": jnp.asarray(2.0)})
        ema.update({"w": jnp.asarray(4.0)})
        with ema.apply() as shadow:
            assert float(shadow["w"]) == pytest.approx(3.0)
        ema.restore()


class TestLuSolve:
    def test_conjugate_transpose_complex(self):
        rng = np.random.RandomState(2)
        A = (rng.randn(3, 3) + 1j * rng.randn(3, 3)).astype("complex64")
        A = A + 4 * np.eye(3, dtype="complex64")
        b = (rng.randn(3, 1) + 1j * rng.randn(3, 1)).astype("complex64")
        lu, piv = paddle.linalg.lu(jnp.asarray(A))
        xh = paddle.linalg.lu_solve(jnp.asarray(b), lu, piv, trans="H")
        np.testing.assert_allclose(np.asarray(jnp.conj(jnp.asarray(A)).T
                                              @ xh), b, rtol=2e-3,
                                   atol=1e-3)

    def test_solves_and_transpose(self):
        rng = np.random.RandomState(0)
        A = rng.randn(4, 4).astype("float32") + 4 * np.eye(4, dtype="float32")
        b = rng.randn(4, 2).astype("float32")
        lu, piv = paddle.linalg.lu(jnp.asarray(A))
        x = paddle.linalg.lu_solve(jnp.asarray(b), lu, piv)
        np.testing.assert_allclose(np.asarray(jnp.asarray(A) @ x), b,
                                   rtol=2e-4, atol=1e-4)
        xt = paddle.linalg.lu_solve(jnp.asarray(b), lu, piv, trans="T")
        np.testing.assert_allclose(np.asarray(jnp.asarray(A).T @ xt), b,
                                   rtol=2e-4, atol=1e-4)

    def test_batched(self):
        rng = np.random.RandomState(1)
        A = rng.randn(3, 4, 4).astype("float32") + 4 * np.eye(
            4, dtype="float32")
        b = rng.randn(3, 4, 1).astype("float32")
        lu, piv = paddle.linalg.lu(jnp.asarray(A))
        x = paddle.linalg.lu_solve(jnp.asarray(b), lu, piv)
        np.testing.assert_allclose(np.asarray(jnp.asarray(A) @ x), b,
                                   rtol=2e-4, atol=1e-4)


class TestMiscWave6:
    def test_tensor_apply(self):
        from paddle_tpu.compat import enable_tensor_methods
        enable_tensor_methods()
        t = jnp.ones(3)
        assert float(t.apply(lambda v: v * 3)[0]) == 3.0

    def test_saved_tensors_hooks_warn_once_noop(self):
        import paddle_tpu.autograd as AG
        AG._STH_WARNED[0] = False
        with warnings.catch_warnings(record=True) as w:
            warnings.simplefilter("always")
            with AG.saved_tensors_hooks(lambda x: x, lambda x: x):
                pass
            with AG.saved_tensors_hooks(lambda x: x, lambda x: x):
                pass
        assert sum("saved_tensors_hooks" in str(x.message) for x in w) == 1

    def test_incubate_multiprocessing(self):
        import paddle_tpu.incubate.multiprocessing as pmp
        assert hasattr(pmp, "Process")
        pmp.set_sharing_strategy("file_system")
        assert pmp.get_sharing_strategy() == "file_system"
