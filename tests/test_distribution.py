"""paddle.distribution parity (reference: python/paddle/distribution/)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu
from paddle_tpu.distribution import (Normal, Uniform, Bernoulli, Categorical,
                                     Beta, Dirichlet, Laplace, Gumbel,
                                     LogNormal, kl_divergence)


def test_normal_logprob_entropy_sample_stats():
    paddle_tpu.seed(0)
    d = Normal(1.0, 2.0)
    lp = float(d.log_prob(jnp.asarray(1.0)))
    np.testing.assert_allclose(lp, -np.log(2.0) - 0.5 * np.log(2 * np.pi),
                               rtol=1e-6)
    s = d.sample((20000,))
    assert abs(float(jnp.mean(s)) - 1.0) < 0.1
    assert abs(float(jnp.std(s)) - 2.0) < 0.1
    np.testing.assert_allclose(float(d.entropy()),
                               0.5 * np.log(2 * np.pi * np.e * 4), rtol=1e-5)
    assert abs(float(d.cdf(jnp.asarray(1.0))) - 0.5) < 1e-6


def test_categorical_and_bernoulli():
    paddle_tpu.seed(1)
    c = Categorical(logits=jnp.log(jnp.asarray([0.2, 0.3, 0.5])))
    lp = np.asarray(c.log_prob(jnp.asarray([0, 2])))
    np.testing.assert_allclose(lp, np.log([0.2, 0.5]), rtol=1e-5)
    samp = np.asarray(c.sample((8000,)))
    frac2 = (samp == 2).mean()
    assert abs(frac2 - 0.5) < 0.05
    b = Bernoulli(probs=0.7)
    np.testing.assert_allclose(float(b.log_prob(jnp.asarray(1.0))),
                               np.log(0.7), rtol=1e-5)


def test_beta_dirichlet_mean_logprob():
    be = Beta(2.0, 3.0)
    np.testing.assert_allclose(float(be.mean), 0.4, rtol=1e-6)
    # log_prob integrates ~ to 1 (trapezoid over grid)
    xs = np.linspace(1e-3, 1 - 1e-3, 2001)
    ps = np.exp(np.asarray(be.log_prob(jnp.asarray(xs))))
    np.testing.assert_allclose(np.trapezoid(ps, xs), 1.0, rtol=1e-3)
    di = Dirichlet(jnp.asarray([1.0, 2.0, 3.0]))
    np.testing.assert_allclose(np.asarray(di.mean),
                               [1 / 6, 2 / 6, 3 / 6], rtol=1e-6)


def test_kl_registrations():
    kl = kl_divergence(Normal(0.0, 1.0), Normal(0.0, 1.0))
    np.testing.assert_allclose(float(kl), 0.0, atol=1e-7)
    kl2 = kl_divergence(Normal(1.0, 1.0), Normal(0.0, 1.0))
    np.testing.assert_allclose(float(kl2), 0.5, rtol=1e-6)
    c1 = Categorical(logits=jnp.zeros(4))
    c2 = Categorical(logits=jnp.log(jnp.asarray([0.7, 0.1, 0.1, 0.1])))
    assert float(kl_divergence(c1, c2)) > 0
    with pytest.raises(NotImplementedError):
        kl_divergence(Normal(0.0, 1.0), Beta(1.0, 1.0))


def test_samples_reproducible_with_seed():
    paddle_tpu.seed(42)
    a = Normal(0.0, 1.0).sample((4,))
    paddle_tpu.seed(42)
    b = Normal(0.0, 1.0).sample((4,))
    np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_laplace_gumbel_lognormal_logprob_finite():
    for d, v in [(Laplace(0.0, 1.0), 0.5), (Gumbel(0.0, 1.0), 0.3),
                 (LogNormal(0.0, 1.0), 1.5)]:
        assert np.isfinite(float(d.log_prob(jnp.asarray(v))))
        s = d.sample((100,))
        assert np.isfinite(np.asarray(s)).all()


def test_kl_uniform_support_guard():
    from paddle_tpu.distribution import Uniform
    assert np.isinf(float(kl_divergence(Uniform(0.0, 2.0),
                                        Uniform(0.0, 1.0))))
    np.testing.assert_allclose(
        float(kl_divergence(Uniform(0.25, 0.75), Uniform(0.0, 1.0))),
        np.log(2.0), rtol=1e-6)
