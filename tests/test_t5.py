"""T5 encoder-decoder oracles: weight-mapped parity vs transformers.T5Model
(config-only, relative position buckets, cross-attention, RMS norms,
unscaled attention) + seq2seq training smoke."""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

import paddle_tpu
from paddle_tpu.models import (T5Config, T5Model, T5ForConditionalGeneration,
                               t5_tiny)
from paddle_tpu.nn.functional_call import functional_call, state


def test_t5_matches_transformers_weight_mapped():
    from transformers import T5Config as HFConfig, T5Model as HFModel
    hf_cfg = HFConfig(vocab_size=256, d_model=64, d_kv=16, d_ff=128,
                      num_layers=2, num_decoder_layers=2, num_heads=4,
                      relative_attention_num_buckets=8,
                      relative_attention_max_distance=20,
                      dropout_rate=0.0, feed_forward_proj="relu",
                      tie_word_embeddings=True, is_gated_act=False)
    torch.manual_seed(0)
    hf = HFModel(hf_cfg).eval()

    paddle_tpu.seed(0)
    mine = T5Model(t5_tiny())
    mine.eval()
    mapped, _ = state(mine)
    mapped = dict(mapped)
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}

    mapped["shared.weight"] = jnp.asarray(sd["shared.weight"])
    for stack, hfs in (("encoder", "encoder"), ("decoder", "decoder")):
        mapped[f"{stack}.final_layer_norm.weight"] = jnp.asarray(
            sd[f"{hfs}.final_layer_norm.weight"])
        for i in range(2):
            hp = f"{hfs}.block.{i}.layer"
            mp = f"{stack}.block.{i}"
            # layer.0 = self-attn, layer.-1 = ff; decoder layer.1 = cross
            for nm, me in (("q", "q"), ("k", "k"), ("v", "v"), ("o", "o")):
                mapped[f"{mp}.self_attn.{me}.weight"] = jnp.asarray(
                    sd[f"{hp}.0.SelfAttention.{nm}.weight"].T)
            mapped[f"{mp}.self_norm.weight"] = jnp.asarray(
                sd[f"{hp}.0.layer_norm.weight"])
            if i == 0:
                mapped[f"{mp}.self_attn.relative_attention_bias.weight"] = \
                    jnp.asarray(
                        sd[f"{hp}.0.SelfAttention"
                           f".relative_attention_bias.weight"])
            if stack == "decoder":
                for nm in ("q", "k", "v", "o"):
                    mapped[f"{mp}.cross_attn.{nm}.weight"] = jnp.asarray(
                        sd[f"{hp}.1.EncDecAttention.{nm}.weight"].T)
                mapped[f"{mp}.cross_norm.weight"] = jnp.asarray(
                    sd[f"{hp}.1.layer_norm.weight"])
                ff_idx = 2
            else:
                ff_idx = 1
            mapped[f"{mp}.ff.wi.weight"] = jnp.asarray(
                sd[f"{hp}.{ff_idx}.DenseReluDense.wi.weight"].T)
            mapped[f"{mp}.ff.wo.weight"] = jnp.asarray(
                sd[f"{hp}.{ff_idx}.DenseReluDense.wo.weight"].T)
            mapped[f"{mp}.ff_norm.weight"] = jnp.asarray(
                sd[f"{hp}.{ff_idx}.layer_norm.weight"])

    rs = np.random.RandomState(1)
    enc_ids = rs.randint(0, 256, (2, 10))
    dec_ids = rs.randint(0, 256, (2, 7))
    enc_mask = np.ones((2, 10), np.int64)
    enc_mask[1, 7:] = 0

    with torch.no_grad():
        ref = hf(input_ids=torch.tensor(enc_ids),
                 decoder_input_ids=torch.tensor(dec_ids),
                 attention_mask=torch.tensor(enc_mask))
    (dec, enc), _ = functional_call(
        mine, mapped, {},
        (jnp.asarray(enc_ids), jnp.asarray(dec_ids),
         jnp.asarray(enc_mask)), train=False)

    np.testing.assert_allclose(np.asarray(enc),
                               ref.encoder_last_hidden_state.numpy(),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(np.asarray(dec),
                               ref.last_hidden_state.numpy(),
                               rtol=2e-4, atol=2e-4)


def test_t5_conditional_generation_trains():
    paddle_tpu.seed(3)
    cfg = t5_tiny()
    model = T5ForConditionalGeneration(cfg)
    model.train()
    params, buffers = state(model)
    import paddle_tpu.optimizer as opt
    o = opt.AdamW(learning_rate=3e-3)
    ostate = o.init(params)
    rs = np.random.RandomState(4)
    enc_ids = jnp.asarray(rs.randint(0, 256, (4, 12)))
    dec_ids = jnp.asarray(rs.randint(0, 256, (4, 8)))
    labels = dec_ids

    @jax.jit
    def step(p, os_):
        def loss_fn(p):
            from paddle_tpu.nn.functional_call import bind_state
            with bind_state(model, p, buffers):
                return model.loss(enc_ids, dec_ids, labels)
        l, g = jax.value_and_grad(loss_fn)(p)
        newp, nos = o.update(g, os_, p)
        return newp, nos, l

    losses = []
    for _ in range(12):
        params, ostate, loss = step(params, ostate)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.8, (losses[0], losses[-1])
