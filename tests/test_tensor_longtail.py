"""Long-tail tensor APIs added in round 4 (VERDICT r3 item 6: close the
found coverage gaps and test them — torch oracles where torch has the
same op, hand oracles elsewhere)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
import torch

import paddle_tpu as paddle


def test_block_diag_matches_torch():
    a = np.random.RandomState(0).randn(2, 3).astype(np.float32)
    b = np.random.RandomState(1).randn(1, 4).astype(np.float32)
    c = np.random.RandomState(2).randn(3, 2).astype(np.float32)
    mine = np.asarray(paddle.block_diag(
        [jnp.asarray(a), jnp.asarray(b), jnp.asarray(c)]))
    ref = torch.block_diag(torch.tensor(a), torch.tensor(b),
                           torch.tensor(c)).numpy()
    np.testing.assert_allclose(mine, ref)


@pytest.mark.parametrize("p", [2.0, 1.0, float("inf"), 0.5])
def test_cdist_matches_torch(p):
    rs = np.random.RandomState(0)
    x = rs.randn(4, 6).astype(np.float32)
    y = rs.randn(5, 6).astype(np.float32)
    mine = np.asarray(paddle.cdist(jnp.asarray(x), jnp.asarray(y), p=p))
    ref = torch.cdist(torch.tensor(x), torch.tensor(y), p=p).numpy()
    np.testing.assert_allclose(mine, ref, rtol=2e-5, atol=2e-5)


def test_cdist_batched_mm_path():
    rs = np.random.RandomState(3)
    x = rs.randn(2, 30, 8).astype(np.float32)   # >25 rows: gram path
    y = rs.randn(2, 40, 8).astype(np.float32)
    mine = np.asarray(paddle.cdist(jnp.asarray(x), jnp.asarray(y)))
    ref = torch.cdist(torch.tensor(x), torch.tensor(y)).numpy()
    np.testing.assert_allclose(mine, ref, rtol=1e-3, atol=1e-3)


def test_fill_diagonal_basic_and_offsetless_wide():
    fd = np.asarray(paddle.fill_diagonal_(jnp.zeros((3, 5)), 7.0))
    ref = torch.zeros(3, 5)
    ref.fill_diagonal_(7.0)
    np.testing.assert_allclose(fd, ref.numpy())


def test_fill_diagonal_wrap_tall():
    fd = np.asarray(paddle.fill_diagonal_(jnp.zeros((7, 3)), 1.0,
                                          wrap=True))
    ref = torch.zeros(7, 3)
    ref.fill_diagonal_(1.0, wrap=True)
    np.testing.assert_allclose(fd, ref.numpy())


def test_fill_diagonal_tensor_2d():
    y = jnp.arange(3.0)
    out = np.asarray(paddle.fill_diagonal_tensor(jnp.zeros((3, 4)), y))
    assert out[0, 0] == 0 and out[1, 1] == 1 and out[2, 2] == 2
    assert out.sum() == 3.0


def test_fill_diagonal_tensor_batched():
    """Batched layout: y = x.shape minus (dim1, dim2) plus diag length
    (review r4: the first cut crashed on every batched call)."""
    x = jnp.zeros((2, 3, 4))
    y = jnp.asarray(np.arange(6.0).reshape(2, 3))
    out = np.asarray(paddle.fill_diagonal_tensor(x, y, dim1=1, dim2=2))
    for b in range(2):
        for i in range(3):
            assert out[b, i, i] == b * 3 + i
    assert out.sum() == 15.0


def test_cholesky_inverse():
    rs = np.random.RandomState(2)
    A = rs.randn(4, 4)
    A = A @ A.T + 4 * np.eye(4)
    L = np.linalg.cholesky(A)
    inv = np.asarray(paddle.tensor.linalg.cholesky_inverse(jnp.asarray(L)))
    np.testing.assert_allclose(inv, np.linalg.inv(A), rtol=1e-6, atol=1e-6)
    U = L.T
    inv_u = np.asarray(paddle.tensor.linalg.cholesky_inverse(
        jnp.asarray(U), upper=True))
    np.testing.assert_allclose(inv_u, np.linalg.inv(A), rtol=1e-6,
                               atol=1e-6)


def test_vecdot():
    v = paddle.tensor.linalg.vecdot(jnp.ones((2, 3)), 2 * jnp.ones((2, 3)))
    np.testing.assert_allclose(np.asarray(v), [6.0, 6.0])


def test_positive_and_bool_error():
    assert float(paddle.positive(jnp.asarray(-2.5))) == -2.5
    with pytest.raises(TypeError):
        paddle.positive(jnp.asarray([True]))


def test_erfc():
    x = jnp.asarray([0.0, 0.5, -1.0])
    np.testing.assert_allclose(
        np.asarray(paddle.erfc(x)),
        torch.special.erfc(torch.tensor([0.0, 0.5, -1.0])).numpy(),
        rtol=1e-6, atol=1e-6)


def test_bitwise_invert():
    np.testing.assert_array_equal(
        np.asarray(paddle.bitwise_invert(jnp.asarray([0, 5], jnp.int32))),
        [-1, -6])


def test_printoptions_roundtrip():
    old = paddle.get_printoptions()
    try:
        paddle.set_printoptions(precision=3, threshold=10)
        got = paddle.get_printoptions()
        assert got["precision"] == 3 and got["threshold"] == 10
        # None keeps current values (paddle semantics)
        paddle.set_printoptions(edgeitems=2)
        assert paddle.get_printoptions()["precision"] == 3
    finally:
        paddle.set_printoptions(**old)


def test_inplace_alias_surface():
    """Every generated alias resolves and computes the out-of-place op."""
    import paddle_tpu.tensor.inplace as ip
    assert len(ip.__all__) >= 70
    x = jnp.asarray([4.0])
    assert float(paddle.sqrt_(x)[0]) == 2.0
    assert float(paddle.rsqrt_(x)[0]) == 0.5
    assert float(paddle.clip_(jnp.asarray([5.0]), 0.0, 1.0)[0]) == 1.0
    np.testing.assert_allclose(np.asarray(paddle.triu_(jnp.ones((2, 2)))),
                               [[1, 1], [0, 1]])
    assert float(paddle.scale_(jnp.asarray([2.0]), scale=3.0)[0]) == 6.0
    assert float(paddle.sigmoid_(jnp.asarray(0.0))) == 0.5


def test_inplace_random_family():
    paddle.seed(0)
    u = paddle.uniform_(jnp.zeros((200,)), min=2.0, max=3.0)
    assert float(u.min()) >= 2.0 and float(u.max()) <= 3.0
    n = paddle.normal_(jnp.zeros((2000,)), mean=5.0, std=0.1)
    assert 4.9 < float(n.mean()) < 5.1
    b = paddle.bernoulli_(jnp.zeros((10,)), p=1.0)
    assert float(b.sum()) == 10.0
    c = paddle.cauchy_(jnp.zeros((100,)))
    assert np.isfinite(np.asarray(c)).all()
    ln = paddle.log_normal_(jnp.zeros((100,)))
    assert float(ln.min()) > 0.0
    z = paddle.zero_(jnp.ones((3,)))
    assert float(z.sum()) == 0.0
    f = paddle.fill_(jnp.zeros((3,)), 2.5)
    assert float(f.sum()) == 7.5


def test_row_stack_alias():
    out = paddle.row_stack([jnp.ones((2,)), jnp.zeros((2,))])
    assert out.shape == (2, 2)
