"""Pipeline schedule tests: fused scan+ppermute vs serial oracle, and the
interleaved (VPP) variant (reference: PipelineParallelWithInterleave;
test/collective/fleet hybrid PP runners assert parallel == serial)."""

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu  # noqa: F401
from paddle_tpu.distributed.pipelining import (
    pipeline_apply, pipeline_apply_interleaved, stack_stage_params,
    stack_interleaved_stage_params)


def _mesh(pp):
    devs = np.asarray(jax.devices()[:pp])
    return Mesh(devs, ("pp",))


def _stage_fn(p, x):
    return jnp.tanh(x @ p["w"] + p["b"])


def _chunks(n, d, seed=0):
    rs = np.random.RandomState(seed)
    return [{"w": jnp.asarray(rs.randn(d, d) * 0.4, jnp.float32),
             "b": jnp.asarray(rs.randn(d) * 0.1, jnp.float32)}
            for _ in range(n)]


def _serial(chunks, xs):
    M = xs.shape[0]
    outs = []
    for m in range(M):
        h = xs[m]
        for c in chunks:
            h = _stage_fn(c, h)
        outs.append(h)
    return jnp.stack(outs)


def _stage_fn_scanning(p, x):
    # pipeline_apply's contract: the body scans its local leading block dim
    def one(h, blk):
        return _stage_fn(blk, h), None
    out, _ = jax.lax.scan(one, x, p)
    return out


def test_fused_pipeline_matches_serial_pp4():
    S, d, M = 4, 16, 8
    chunks = _chunks(S, d)
    rs = np.random.RandomState(1)
    xs = jnp.asarray(rs.randn(M, 4, d), jnp.float32)
    stacked = stack_stage_params(chunks)
    out = pipeline_apply(_stage_fn_scanning, stacked, xs, _mesh(S), S,
                         remat=False)
    ref = _serial(chunks, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_interleaved_pipeline_matches_serial():
    # S=2 devices x V=2 chunks: 4 global chunks round-robin (dev0: 0,2;
    # dev1: 1,3)
    S, V, d, M = 2, 2, 16, 8
    chunks = _chunks(S * V, d, seed=2)
    rs = np.random.RandomState(3)
    xs = jnp.asarray(rs.randn(M, 4, d), jnp.float32)
    stacked = stack_interleaved_stage_params(chunks, S, V)
    out = pipeline_apply_interleaved(_stage_fn, stacked, xs, _mesh(S), S, V,
                                     remat=False)
    ref = _serial(chunks, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_interleaved_pipeline_pp4_v2():
    S, V, d, M = 4, 2, 8, 8
    chunks = _chunks(S * V, d, seed=4)
    rs = np.random.RandomState(5)
    xs = jnp.asarray(rs.randn(M, 2, d), jnp.float32)
    stacked = stack_interleaved_stage_params(chunks, S, V)
    out = pipeline_apply_interleaved(_stage_fn, stacked, xs, _mesh(S), S, V,
                                     remat=False)
    ref = _serial(chunks, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_interleaved_pipeline_grads_match_serial():
    S, V, d, M = 2, 2, 8, 4
    chunks = _chunks(S * V, d, seed=6)
    rs = np.random.RandomState(7)
    xs = jnp.asarray(rs.randn(M, 2, d), jnp.float32)
    mesh = _mesh(S)

    def loss_pipe(chs):
        stacked = stack_interleaved_stage_params(chs, S, V)
        out = pipeline_apply_interleaved(_stage_fn, stacked, xs, mesh, S, V,
                                         remat=True)
        return jnp.sum(out ** 2)

    def loss_serial(chs):
        return jnp.sum(_serial(chs, xs) ** 2)

    g_pipe = jax.grad(loss_pipe)(chunks)
    g_ser = jax.grad(loss_serial)(chunks)
    for gp, gs in zip(g_pipe, g_ser):
        for k in gp:
            np.testing.assert_allclose(np.asarray(gp[k]), np.asarray(gs[k]),
                                       rtol=1e-4, atol=1e-4)


def test_pipeline_forward_lowers_without_allreduce():
    """Compile-level oracle for the round-3 output-collection rewrite:
    the FORWARD pipeline program contains collective-permutes (the ring)
    but NO all-reduce — the old per-tick psum broadcast is gone from the
    lowered HLO, not just from the Python source."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    import numpy as np
    from paddle_tpu.distributed.pipelining import pipeline_apply

    devs = np.array(jax.devices()[:4]).reshape(4)
    mesh = Mesh(devs, ("pp",))
    S, M, mb, h = 4, 4, 2, 8
    params = {"w": jnp.stack([jnp.eye(h) * (i + 1) for i in range(S)])}

    def stage(p, x):
        return jnp.tanh(x @ p["w"][0])

    xs = jnp.ones((M, mb, h))

    def fwd(params, xs):
        return pipeline_apply(stage, params, xs, mesh, S, remat=False)

    txt = jax.jit(fwd).lower(params, xs).compile().as_text()
    assert "collective-permute" in txt       # the ppermute ring is there
    import re
    ars = [ln for ln in re.findall(r"all-reduce[^\n]*", txt)
           if "= f32" in ln or ln.startswith("all-reduce = ")]
    # exactly ONE all-reduce: the end-of-schedule gather of the last
    # stage's rows (lowered from the caller-side dynamic_slice over the
    # pp-stacked output).  The old design all-reduced INSIDE the scan —
    # T per-tick activation broadcasts; that pattern would show up here
    # as an all-reduce within the while-loop body.
    assert len(ars) == 1, ars


def test_pipeline_schedule_sweep_forward_and_grads():
    """Parameter sweep over (S, V, M, width): every schedule shape ==
    serial oracle for BOTH outputs and parameter gradients (seeded random
    stacks — the schedule-correctness analog of the op fuzzer)."""
    rs = np.random.RandomState(42)
    configs = [(2, 1, 2), (2, 1, 5), (4, 1, 4), (2, 2, 2), (2, 2, 4),
               (4, 2, 4)]
    for idx, (S, V, M) in enumerate(configs):
        d = int(rs.choice([4, 8]))
        mb = int(rs.choice([1, 2]))
        chunks = _chunks(S * V, d, seed=100 + idx)
        xs = jnp.asarray(rs.randn(M, mb, d) * 0.5, jnp.float32)
        mesh = _mesh(S)

        if V == 1:
            def run(chs):
                st = stack_stage_params(chs)
                return pipeline_apply(_stage_fn_scanning, st, xs, mesh, S,
                                      remat=bool(idx % 2))
        else:
            def run(chs):
                st = stack_interleaved_stage_params(chs, S, V)
                return pipeline_apply_interleaved(
                    _stage_fn, st, xs, mesh, S, V, remat=bool(idx % 2))

        # remat (jax.checkpoint) inside shard_map needs the call jitted
        out = jax.jit(run)(chunks)
        ref = _serial(chunks, xs)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5,
                                   err_msg=f"fwd S{S} V{V} M{M}")

        g_pipe = jax.jit(jax.grad(lambda c: jnp.sum(run(c) ** 2)))(chunks)
        g_ser = jax.grad(lambda c: jnp.sum(_serial(c, xs) ** 2))(chunks)
        for gp, gs in zip(g_pipe, g_ser):
            for k in gp:
                np.testing.assert_allclose(
                    np.asarray(gp[k]), np.asarray(gs[k]),
                    rtol=5e-4, atol=5e-4,
                    err_msg=f"grad S{S} V{V} M{M} {k}")
