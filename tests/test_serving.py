"""Continuous-batching serving engine (paddle_tpu.serving).

The load-bearing contracts:
  * engine greedy output == ``model.generate`` token-for-token on
    mixed-length prompts (the engine is a scheduler around the SAME
    decode arithmetic, so exact equality is the bar, not tolerance);
  * per-slot sampling reproduces ``generate(seed=...)`` exactly for a
    single request (same key-split discipline);
  * slot eviction/reuse and FCFS admission under over-subscription;
  * the compile-count guard: a mixed-length workload lowers at most
    O(num_buckets) prefill programs + ONE decode program.

Most GPT tests share one module-scoped engine (every test drains the
requests it submits, so the pool is empty between tests) and a standard
prompt-length set, so jit caches amortize across the file; the
compile-count test builds its own instance because it asserts on trace
counters from a cold start.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import (GPTForCausalLM, LlamaConfig,
                               LlamaForCausalLM, gpt_tiny)
from paddle_tpu.serving import (KVPool, SamplingParams, Scheduler,
                                ServingEngine, bucket_length)


@pytest.fixture(scope="module")
def gpt():
    with jax.default_prng_impl("rbg"):
        return GPTForCausalLM(gpt_tiny())


@pytest.fixture(scope="module")
def eng(gpt):
    return ServingEngine(gpt, num_slots=3, min_bucket=8)


def _prompts(seed, lengths, vocab=256):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, vocab, (L,)) for L in lengths]


def _want_tokens(model, prompt, n=5, **kw):
    """Oracle: the single-request generate() tail for the same prompt."""
    seq = model.generate(jnp.asarray(prompt)[None], max_new_tokens=n, **kw)
    return np.asarray(seq)[0, len(prompt):]


# ------------------------------------------------------------ correctness

def test_greedy_matches_generate_mixed_lengths(gpt, eng):
    prompts = _prompts(0, (3, 7, 12, 5))
    outs = eng.serve_batch(prompts, max_new_tokens=5, max_steps=200)
    for p, o in zip(prompts, outs):
        assert o.finished and o.finish_reason == "length"
        np.testing.assert_array_equal(np.asarray(o.tokens),
                                      _want_tokens(gpt, p))
        np.testing.assert_array_equal(
            o.sequence, np.concatenate([p, _want_tokens(gpt, p)]))


def test_sampling_matches_generate_single_request(gpt, eng):
    """Per-slot keys follow generate()'s split discipline, so a lone
    sampled request reproduces generate(seed=...) exactly."""
    p = _prompts(1, (7,))[0]
    sp = SamplingParams(do_sample=True, temperature=1.7, top_k=9,
                        top_p=0.85, seed=11)
    rid = eng.submit(p, max_new_tokens=5, sampling=sp)
    eng.run_until_complete(100)
    want = _want_tokens(gpt, p, do_sample=True, temperature=1.7,
                        top_k=9, top_p=0.85, seed=11)
    np.testing.assert_array_equal(np.asarray(eng.result(rid).tokens), want)


def test_sampling_per_slot_isolation(gpt, eng):
    """Concurrent requests with DIFFERENT sampling params each match
    their solo generate() run — one slot's randomness/filters never
    leak into a neighbour."""
    prompts = _prompts(2, (3, 7, 5))
    params = [SamplingParams(),                                   # greedy
              SamplingParams(do_sample=True, temperature=2.0, seed=3),
              SamplingParams(do_sample=True, top_k=5, top_p=0.7, seed=4)]
    rids = [eng.submit(p, max_new_tokens=5, sampling=s)
            for p, s in zip(prompts, params)]
    eng.run_until_complete(100)
    wants = [_want_tokens(gpt, prompts[0]),
             _want_tokens(gpt, prompts[1], do_sample=True,
                          temperature=2.0, seed=3),
             _want_tokens(gpt, prompts[2], do_sample=True, top_k=5,
                          top_p=0.7, seed=4)]
    for rid, want in zip(rids, wants):
        np.testing.assert_array_equal(np.asarray(eng.result(rid).tokens),
                                      want)


def test_eos_finishes_early(gpt, eng):
    p = _prompts(3, (7,))[0]
    free = _want_tokens(gpt, p)
    eos = int(free[2])              # greedy emits this at step 2 of 5
    rid = eng.submit(p, max_new_tokens=5, eos_token_id=eos)
    eng.run_until_complete(100)
    out = eng.result(rid)
    assert out.finish_reason == "eos"
    stop = int(np.flatnonzero(free == eos)[0])
    np.testing.assert_array_equal(np.asarray(out.tokens), free[:stop + 1])


def test_llama_engine_greedy_parity():
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    prompts = _prompts(4, (2, 9, 5), vocab=128)
    engine = ServingEngine(model, num_slots=2, min_bucket=8)
    outs = engine.serve_batch(prompts, max_new_tokens=4, max_steps=100)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(np.asarray(o.tokens),
                                      _want_tokens(model, p, 4))


# ----------------------------------------------------- scheduling / slots

def test_slot_eviction_reuse_and_oversubscription(gpt, eng):
    """8 requests through 3 slots: every slot is reused, admission stays
    FCFS, the queue drains, and outputs still match generate()."""
    eng.metrics.reset()
    prompts = _prompts(5, (3, 5, 7, 5, 9, 7, 3, 5))
    rids = [eng.submit(p, max_new_tokens=5) for p in prompts]
    assert eng.core.scheduler.queue_depth == 8    # nothing admitted yet
    eng.step()
    assert eng.core.scheduler.active == 3         # all slots filled
    assert eng.core.scheduler.queue_depth == 5
    eng.run_until_complete(200)
    assert eng.core.pool.free_slots == 3
    assert eng.core.scheduler.queue_depth == 0
    m = eng.metrics_dict()
    assert m["requests_finished"] == 8
    assert m["prefills"] == 8                     # every slot re-prefilled
    # FCFS: with equal max_new_tokens, the first submission finishes
    # before the last (later arrivals wait for freed slots)
    times = [eng._requests[r].finish_time for r in rids]
    assert all(t is not None for t in times)
    assert times[0] < times[-1]
    for p, rid in zip(prompts, rids):
        np.testing.assert_array_equal(
            np.asarray(eng.result(rid).tokens), _want_tokens(gpt, p))


def test_pool_alloc_free_cycle():
    pool = KVPool(num_slots=2, max_seq=16, num_layers=1, kv_heads=2,
                  head_dim=4)
    a, b = pool.alloc(), pool.alloc()
    assert {a, b} == {0, 1} and pool.free_slots == 0
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.alloc()
    pool.free(a)
    with pytest.raises(ValueError, match="double free"):
        pool.free(a)
    assert pool.alloc() == a                      # lowest slot reused
    pool.reset()
    assert pool.free_slots == 2


def test_scheduler_validation_and_buckets():
    sched = Scheduler(num_slots=2, max_seq=128, min_bucket=16)
    assert sched.bucket(1) == 16
    assert sched.bucket(16) == 16
    assert sched.bucket(17) == 32
    assert sched.bucket(100) == 128               # pow2 capped at max_seq
    assert bucket_length(100, 16, None) == 128
    with pytest.raises(ValueError, match="exceeds"):
        bucket_length(200, 16, 128)
    with pytest.raises(ValueError, match="temperature"):
        SamplingParams(do_sample=True, temperature=0.0).validate()
    with pytest.raises(ValueError, match="top_p"):
        SamplingParams(top_p=0.0).validate()


def test_submit_rejects_overlong(gpt, eng):
    with pytest.raises(ValueError, match="max_seq"):
        eng.submit(np.zeros(120, np.int32), max_new_tokens=20)  # > 128


# ------------------------------------------------------- compile bounding

def test_compile_count_is_bucket_bounded(gpt):
    """THE fixed-shape contract: a mixed-length mixed-arrival workload
    lowers at most one prefill program per pow2 bucket plus ONE decode
    program — prompt length diversity must never leak into the compile
    cache (trace counters tick only when jit actually traces)."""
    lengths = (3, 5, 8, 9, 13, 17, 20, 31, 6, 11)
    buckets = {bucket_length(L, 8, 128) for L in lengths}   # {8, 16, 32}
    engine = ServingEngine(gpt, num_slots=3, min_bucket=8)
    rids = [engine.submit(p, max_new_tokens=3 + (i % 3))
            for i, p in enumerate(_prompts(6, lengths))]
    engine.run_until_complete(500)
    assert all(engine.result(r).finished for r in rids)
    assert engine.core.trace_counts["decode"] == 1
    assert engine.core.trace_counts["prefill"] == len(buckets) == 3


# ------------------------------------------------------ streaming / misc

def test_stream_yields_tokens_incrementally(gpt, eng):
    p = _prompts(7, (5,))[0]
    rid = eng.submit(p, max_new_tokens=5)
    got = list(eng.stream(rid))
    np.testing.assert_array_equal(np.asarray(got), _want_tokens(gpt, p))
    assert eng.result(rid).finished


def test_stream_callback_fires_per_token(gpt, eng):
    p = _prompts(8, (7,))[0]
    seen = []
    rid = eng.submit(p, max_new_tokens=5,
                     stream=lambda req, tok: seen.append(tok))
    eng.run_until_complete(100)
    assert seen == eng.result(rid).tokens


def test_metrics_snapshot_and_purge(gpt, eng):
    eng.metrics.reset()
    before = set(eng._requests)
    outs = eng.serve_batch(_prompts(9, (3, 5, 9)), max_new_tokens=3,
                           max_steps=100)
    m = eng.metrics_dict()
    assert m["requests_submitted"] == m["requests_finished"] == 3
    assert m["tokens_generated"] == 9
    assert m["prefill_tokens"] == 17
    assert 0 < m["batch_fill_ratio"] <= 1.0
    assert m["tokens_per_sec"] > 0
    assert m["mean_ttft_ms"] > 0
    assert all(o.ttft_s is not None and o.ttft_s >= 0 for o in outs)
    # serve_batch purges its requests — batch after batch, no growth
    assert set(eng._requests) == before


def test_run_until_complete_max_steps_guard(gpt, eng):
    rid = eng.submit(_prompts(10, (4,))[0], max_new_tokens=10)
    with pytest.raises(RuntimeError, match="did not drain"):
        eng.run_until_complete(max_steps=2)
    eng.run_until_complete(100)                   # drain for later tests
    assert eng.result(rid).finished


def test_inference_predictor_routes_to_engine(gpt):
    """Config(model=<causal-LM>) serves through the engine instead of
    requiring a jit.save artifact; ragged prompt_lens round-trip."""
    from paddle_tpu import inference
    cfg = inference.Config(model=gpt).set_serving_options(
        num_slots=2, max_new_tokens=4)
    pred = inference.create_predictor(cfg)
    assert isinstance(pred, inference.ServingPredictor)
    prompts = _prompts(11, (3, 7))
    ids = np.zeros((2, 7), np.int32)
    for i, p in enumerate(prompts):
        ids[i, :len(p)] = p
    pred.get_input_handle("input_ids").copy_from_cpu(ids)
    pred.get_input_handle("prompt_lens").copy_from_cpu(
        np.asarray([3, 7], np.int32))
    assert pred.run()
    toks = pred.get_output_handle("generated_ids").copy_to_cpu()
    lens = pred.get_output_handle("generated_lens").copy_to_cpu()
    assert toks.shape == (2, 4) and list(lens) == [4, 4]
    for i, p in enumerate(prompts):
        np.testing.assert_array_equal(toks[i], _want_tokens(gpt, p, 4))


def test_inference_config_rejects_non_model():
    from paddle_tpu import inference
    with pytest.raises(TypeError, match="init_cache"):
        inference.Config(model=object())


def test_admission_failure_releases_resources_and_requeues(gpt, eng):
    """If anything raises after the slot claim (scheduler.place here —
    called only inside _begin_prefill, AFTER alloc + radix match), the
    engine must (a) propagate, (b) return the slot and any radix pins,
    and (c) push the failed + unstarted batch back onto the queue so no
    submitted request is ever lost — then serve them fine once the
    fault clears."""
    core = eng.core
    free_before = core.pool.free_slots
    prompts = _prompts(23, (6, 9))
    rids = [eng.submit(p, max_new_tokens=3) for p in prompts]
    orig = core.scheduler.place
    core.scheduler.place = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("induced admission failure"))
    try:
        with pytest.raises(RuntimeError, match="induced admission"):
            eng.step()
    finally:
        core.scheduler.place = orig
    assert core.pool.free_slots == free_before
    assert core.scheduler.queue_depth == len(prompts)   # nothing lost
    if core.prefix_cache is not None:                   # no leaked pins
        stack = list(core.prefix_cache.root.children.values())
        while stack:
            n = stack.pop()
            assert n.refcount == 0
            stack.extend(n.children.values())
    eng.run_until_complete(max_steps=200)
    for rid, p in zip(rids, prompts):
        out = eng.purge(rid)
        assert out.finished
        np.testing.assert_array_equal(out.tokens, _want_tokens(gpt, p, 3))

    # a failed-then-retried admission with a CACHED prefix must count
    # its hit once, not once per attempt (accounting moved after place)
    long_p = _prompts(29, (40,))[0]
    rid = eng.submit(long_p, max_new_tokens=2)
    eng.run_until_complete(max_steps=200)
    eng.purge(rid)                      # prefix now cached
    hits_before = core.metrics.prefix_hits
    rid = eng.submit(long_p, max_new_tokens=2)
    core.scheduler.place = lambda *a, **k: (_ for _ in ()).throw(
        RuntimeError("induced admission failure"))
    try:
        with pytest.raises(RuntimeError, match="induced admission"):
            eng.step()
    finally:
        core.scheduler.place = orig
    eng.run_until_complete(max_steps=200)
    assert eng.purge(rid).finished
    assert core.metrics.prefix_hits == hits_before + 1
