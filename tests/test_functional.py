"""Functional op tests vs numpy references (OpTest-style; model:
test/legacy_test/test_activation_op.py etc.)."""

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu
import paddle_tpu.nn.functional as F


def _np(x):
    return np.asarray(x)


def test_relu_gelu_silu():
    x = np.random.randn(4, 5).astype(np.float32)
    np.testing.assert_allclose(_np(F.relu(jnp.asarray(x))), np.maximum(x, 0))
    sig = 1 / (1 + np.exp(-x))
    np.testing.assert_allclose(_np(F.silu(jnp.asarray(x))), x * sig, rtol=1e-5)
    # gelu tanh approximation vs formula
    ref = 0.5 * x * (1 + np.tanh(np.sqrt(2 / np.pi) * (x + 0.044715 * x**3)))
    np.testing.assert_allclose(_np(F.gelu(jnp.asarray(x), approximate=True)),
                               ref, rtol=1e-3, atol=1e-4)


def test_softmax_matches_numpy():
    x = np.random.randn(3, 7).astype(np.float32)
    e = np.exp(x - x.max(-1, keepdims=True))
    ref = e / e.sum(-1, keepdims=True)
    np.testing.assert_allclose(_np(F.softmax(jnp.asarray(x))), ref, rtol=1e-5)


def test_linear():
    x = np.random.randn(2, 3).astype(np.float32)
    w = np.random.randn(3, 4).astype(np.float32)
    b = np.random.randn(4).astype(np.float32)
    out = F.linear(jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(_np(out), x @ w + b, rtol=1e-5)


def test_conv2d_matches_direct():
    x = np.random.randn(1, 2, 5, 5).astype(np.float32)
    w = np.random.randn(3, 2, 3, 3).astype(np.float32)
    out = F.conv2d(jnp.asarray(x), jnp.asarray(w), padding=1)
    assert out.shape == (1, 3, 5, 5)
    # direct computation at center pixel
    ref = 0.0
    patch = x[0, :, 1:4, 1:4]
    ref = (patch * w[0]).sum()
    np.testing.assert_allclose(_np(out)[0, 0, 2, 2], ref, rtol=1e-4)


def test_layer_norm():
    x = np.random.randn(2, 5).astype(np.float32)
    out = F.layer_norm(jnp.asarray(x), 5)
    mu = x.mean(-1, keepdims=True)
    sd = x.std(-1, keepdims=True)
    np.testing.assert_allclose(_np(out), (x - mu) / np.sqrt(sd**2 + 1e-5),
                               rtol=1e-4, atol=1e-5)


def test_batch_norm_eval():
    x = np.random.randn(4, 3, 2, 2).astype(np.float32)
    rm = np.zeros(3, np.float32)
    rv = np.ones(3, np.float32)
    out = F.batch_norm(jnp.asarray(x), jnp.asarray(rm), jnp.asarray(rv),
                       training=False)
    np.testing.assert_allclose(_np(out), x / np.sqrt(1 + 1e-5), rtol=1e-5)


def test_max_avg_pool():
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    mp = F.max_pool2d(jnp.asarray(x), 2)
    ap = F.avg_pool2d(jnp.asarray(x), 2)
    np.testing.assert_array_equal(_np(mp)[0, 0], [[5, 7], [13, 15]])
    np.testing.assert_allclose(_np(ap)[0, 0], [[2.5, 4.5], [10.5, 12.5]])


def test_adaptive_pool():
    x = np.random.randn(1, 2, 6, 6).astype(np.float32)
    out = F.adaptive_avg_pool2d(jnp.asarray(x), 1)
    np.testing.assert_allclose(_np(out)[0, :, 0, 0], x.mean((2, 3))[0], rtol=1e-5)


def test_cross_entropy_hard_vs_manual():
    logits = np.random.randn(4, 6).astype(np.float32)
    labels = np.array([0, 2, 5, 1])
    out = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(4), labels]).mean()
    np.testing.assert_allclose(float(out), ref, rtol=1e-4)


def test_cross_entropy_ignore_index():
    logits = np.random.randn(4, 6).astype(np.float32)
    labels = np.array([0, -100, 5, -100])
    out = F.cross_entropy(jnp.asarray(logits), jnp.asarray(labels))
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[[0, 2], [0, 5]]).mean()
    np.testing.assert_allclose(float(out), ref, rtol=1e-4)


def test_cross_entropy_soft_label():
    logits = np.random.randn(3, 4).astype(np.float32)
    soft = np.random.dirichlet(np.ones(4), 3).astype(np.float32)
    out = F.cross_entropy(jnp.asarray(logits), jnp.asarray(soft), soft_label=True)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    logp = np.log(e / e.sum(-1, keepdims=True))
    ref = -(soft * logp).sum(-1).mean()
    np.testing.assert_allclose(float(out), ref, rtol=1e-4)


def test_mse_l1_smooth():
    a = np.random.randn(5).astype(np.float32)
    b = np.random.randn(5).astype(np.float32)
    np.testing.assert_allclose(float(F.mse_loss(jnp.asarray(a), jnp.asarray(b))),
                               ((a - b) ** 2).mean(), rtol=1e-5)
    np.testing.assert_allclose(float(F.l1_loss(jnp.asarray(a), jnp.asarray(b))),
                               np.abs(a - b).mean(), rtol=1e-5)


def test_dropout_train_eval():
    x = jnp.ones((100, 100))
    y = F.dropout(x, p=0.5, training=True)
    kept = float((y != 0).mean())
    assert 0.4 < kept < 0.6
    # upscale: kept values are 2.0
    vals = np.unique(_np(y))
    assert set(np.round(vals, 5)).issubset({0.0, 2.0})
    np.testing.assert_array_equal(_np(F.dropout(x, 0.5, training=False)), _np(x))


def test_embedding_padding_idx():
    w = np.random.randn(10, 4).astype(np.float32)
    idx = np.array([[1, 0, 3]])
    out = F.embedding(jnp.asarray(idx), jnp.asarray(w), padding_idx=0)
    np.testing.assert_allclose(_np(out)[0, 0], w[1], rtol=1e-6)
    np.testing.assert_array_equal(_np(out)[0, 1], np.zeros(4))


def test_sdpa_reference_vs_manual():
    np.random.seed(0)
    b, s, h, d = 2, 6, 2, 4
    q = np.random.randn(b, s, h, d).astype(np.float32)
    k = np.random.randn(b, s, h, d).astype(np.float32)
    v = np.random.randn(b, s, h, d).astype(np.float32)
    out = F.scaled_dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v))
    # manual
    qh = q.transpose(0, 2, 1, 3)
    kh = k.transpose(0, 2, 1, 3)
    vh = v.transpose(0, 2, 1, 3)
    logits = qh @ kh.transpose(0, 1, 3, 2) / np.sqrt(d)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = (p @ vh).transpose(0, 2, 1, 3)
    np.testing.assert_allclose(_np(out), ref, rtol=1e-4, atol=1e-5)


def test_sdpa_causal():
    b, s, h, d = 1, 5, 1, 4
    q = np.random.randn(b, s, h, d).astype(np.float32)
    k = np.random.randn(b, s, h, d).astype(np.float32)
    v = np.random.randn(b, s, h, d).astype(np.float32)
    out = F.scaled_dot_product_attention(jnp.asarray(q), jnp.asarray(k),
                                         jnp.asarray(v), is_causal=True)
    # first position attends only to itself
    np.testing.assert_allclose(_np(out)[0, 0, 0], v[0, 0, 0], rtol=1e-4)


def test_one_hot():
    out = F.one_hot(jnp.asarray([0, 2]), 3)
    np.testing.assert_array_equal(_np(out), [[1, 0, 0], [0, 0, 1]])


def test_pad_spatial_form():
    x = jnp.ones((1, 1, 2, 2))
    out = F.pad(x, [1, 1, 0, 0])  # l,r,t,b on W then H (reversed dims)
    assert out.shape == (1, 1, 2, 4)


def test_interpolate_nearest():
    x = jnp.asarray(np.arange(4, dtype=np.float32).reshape(1, 1, 2, 2))
    out = F.interpolate(x, scale_factor=2, mode="nearest")
    assert out.shape == (1, 1, 4, 4)
    np.testing.assert_array_equal(_np(out)[0, 0], np.repeat(
        np.repeat(np.arange(4).reshape(2, 2), 2, 0), 2, 1))


def test_grid_sample_identity_and_affine_grid():
    from paddle_tpu.nn.functional import grid_sample, affine_grid
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(2, 3, 5, 7).astype(np.float32))
    # identity theta -> identity grid -> identity sampling
    theta = jnp.broadcast_to(jnp.asarray([[1.0, 0, 0], [0, 1.0, 0]]),
                             (2, 2, 3))
    grid = affine_grid(theta, (2, 3, 5, 7), align_corners=True)
    out = grid_sample(x, grid, align_corners=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-4,
                               atol=1e-5)
    # horizontal flip theta
    flip = jnp.broadcast_to(jnp.asarray([[-1.0, 0, 0], [0, 1.0, 0]]),
                            (2, 2, 3))
    out_f = grid_sample(x, affine_grid(flip, (2, 3, 5, 7)))
    np.testing.assert_allclose(np.asarray(out_f),
                               np.asarray(x)[:, :, :, ::-1], rtol=1e-4,
                               atol=1e-5)


def test_sequence_mask_and_temporal_shift():
    from paddle_tpu.nn.functional import sequence_mask, temporal_shift
    m = sequence_mask(jnp.asarray([1, 3]), maxlen=4)
    np.testing.assert_array_equal(np.asarray(m),
                                  [[1, 0, 0, 0], [1, 1, 1, 0]])
    x = jnp.asarray(np.arange(2 * 4 * 2 * 1 * 1, dtype=np.float32)
                    .reshape(8, 2, 1, 1))
    out = temporal_shift(x, seg_num=4, shift_ratio=0.25)
    assert out.shape == x.shape


def test_gather_tree_walks_parents():
    from paddle_tpu.nn.functional import gather_tree
    # T=3, B=1, beam=2; parents define the backward walk
    ids = jnp.asarray([[[1, 2]], [[3, 4]], [[5, 6]]])
    parents = jnp.asarray([[[0, 0]], [[0, 0]], [[1, 0]]])
    out = np.asarray(gather_tree(ids, parents))
    # final beam 0 at t=2 came from beam 1 at t=1 (parent=1), which came
    # from beam 0 at t=0
    np.testing.assert_array_equal(out[:, 0, 0], [1, 4, 5])
    np.testing.assert_array_equal(out[:, 0, 1], [1, 3, 6])


def test_npair_loss_positive_and_sane():
    from paddle_tpu.nn.functional import npair_loss
    rs = np.random.RandomState(1)
    a = jnp.asarray(rs.randn(4, 8).astype(np.float32))
    p = jnp.asarray(rs.randn(4, 8).astype(np.float32))
    l = jnp.asarray([0, 1, 2, 3])
    loss = float(npair_loss(a, p, l))
    assert np.isfinite(loss) and loss > 0
    # perfectly aligned embeddings with distinct labels -> small ce
    eye = jnp.eye(4, 8) * 10
    small = float(npair_loss(eye, eye, l, l2_reg=0.0))
    assert small < 0.01


def test_review_fixes_dirac_npair_reflection():
    import jax
    import paddle_tpu.nn.initializer as I
    from paddle_tpu.nn.functional import npair_loss, grid_sample
    key = jax.random.PRNGKey(1)
    # Dirac with out_c > in_c: extra channels stay ZERO (no duplication)
    k = I.Dirac().init(key, (4, 2, 3, 3), jnp.float32)
    np.testing.assert_allclose(np.asarray(k[2:]), 0.0)
    assert float(k[0, 0, 1, 1]) == 1.0 and float(k[1, 1, 1, 1]) == 1.0
    # npair reg uses Beta=0.25
    a = jnp.eye(2, 4)
    got = float(npair_loss(a, a, jnp.asarray([0, 1]), l2_reg=1.0))
    base = float(npair_loss(a, a, jnp.asarray([0, 1]), l2_reg=0.0))
    np.testing.assert_allclose(got - base, 0.25 * 2.0, rtol=1e-5)
    # reflection with a size-1 dim must not NaN
    x = jnp.ones((1, 1, 1, 4))
    g = jnp.zeros((1, 1, 4, 2)).at[..., 1].set(-1.5)
    out = grid_sample(x, g, padding_mode="reflection", align_corners=True)
    assert np.isfinite(np.asarray(out)).all()


def test_additional_losses_oracles():
    import math
    from paddle_tpu.nn import functional as F
    rs = np.random.RandomState(3)
    # soft margin
    x = jnp.asarray([0.5, -1.0])
    y = jnp.asarray([1.0, -1.0])
    ref = np.mean(np.log1p(np.exp(-np.asarray(y) * np.asarray(x))))
    np.testing.assert_allclose(float(F.soft_margin_loss(x, y)), ref,
                               rtol=1e-6)
    # gaussian nll
    g = float(F.gaussian_nll_loss(jnp.asarray([1.0]), jnp.asarray([2.0]),
                                  jnp.asarray([4.0])))
    np.testing.assert_allclose(g, 0.5 * (math.log(4.0) + 1.0 / 4.0),
                               rtol=1e-6)
    # poisson nll (log input)
    pl = float(F.poisson_nll_loss(jnp.asarray([0.0]), jnp.asarray([2.0])))
    np.testing.assert_allclose(pl, 1.0 - 0.0, rtol=1e-6)
    # dice on a perfect prediction -> ~0
    probs = jnp.asarray([[[0.0, 1.0], [1.0, 0.0]]])    # [1, 2, C=2]
    lbl = jnp.asarray([[[1], [0]]])
    assert float(F.dice_loss(probs, lbl)) < 1e-4
    # multi-label soft margin matches manual bce mean
    inp = jnp.asarray([[0.2, -0.4]])
    tgt = jnp.asarray([[1.0, 0.0]])
    import jax as _j
    manual = -np.mean(np.asarray(tgt) * np.asarray(_j.nn.log_sigmoid(inp))
                      + (1 - np.asarray(tgt)) *
                      np.asarray(_j.nn.log_sigmoid(-inp)))
    np.testing.assert_allclose(
        float(F.multi_label_soft_margin_loss(inp, tgt)), manual, rtol=1e-5)


def test_feature_alpha_dropout_channelwise():
    import paddle_tpu
    from paddle_tpu.nn import functional as F
    paddle_tpu.seed(0)
    x = jnp.ones((2, 8, 4, 4))
    out = F.feature_alpha_dropout(x, p=0.5, training=True)
    # whole channels share one fate: each [n, c] slice is constant
    o = np.asarray(out)
    per_channel_std = o.reshape(2, 8, -1).std(axis=-1)
    np.testing.assert_allclose(per_channel_std, 0.0, atol=1e-6)
    assert F.feature_alpha_dropout(x, p=0.5, training=False) is x


def test_lp_pool_matches_torch():
    """lp_pool1d/2d incl. padded border windows and ceil-mode tails
    (review r4: an exclusive average over-counted partial windows)."""
    import torch
    rs = np.random.RandomState(0)
    x = rs.randn(2, 3, 16).astype(np.float32)
    mine = np.asarray(F.lp_pool1d(jnp.asarray(x), 2.0, 4, 4))
    ref = torch.nn.functional.lp_pool1d(torch.tensor(x), 2.0, 4, 4).numpy()
    np.testing.assert_allclose(mine, ref, rtol=1e-5)
    # padded border: avg*k must equal the true window sum
    y = jnp.arange(1.0, 7.0).reshape(1, 1, 6)
    out = np.asarray(F.lp_pool1d(y, 1.0, 3, 3, padding=1))
    np.testing.assert_allclose(out.ravel(), [3.0, 12.0])
    # ceil-mode tail window of 1 element
    out2 = np.asarray(F.lp_pool1d(jnp.ones((1, 1, 5)), 1.0, 2, stride=2,
                                  ceil_mode=True))
    np.testing.assert_allclose(out2.ravel(), [2.0, 2.0, 1.0])
    x2 = rs.randn(2, 3, 8, 8).astype(np.float32)
    m2 = np.asarray(F.lp_pool2d(jnp.asarray(x2), 3.0, 2, 2))
    r2 = torch.nn.functional.lp_pool2d(torch.tensor(x2), 3.0, 2, 2).numpy()
    np.testing.assert_allclose(m2, r2, rtol=1e-4, equal_nan=True)


def test_fractional_max_pool():
    import pytest
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(2, 3, 8, 8).astype(np.float32))
    out = F.fractional_max_pool2d(x, output_size=3, random_u=0.5)
    assert out.shape == (2, 3, 3, 3)
    # deterministic given u; global max survives
    np.testing.assert_allclose(
        np.asarray(out),
        np.asarray(F.fractional_max_pool2d(x, output_size=3, random_u=0.5)))
    assert float(out.max()) == float(x.max())
    o3 = F.fractional_max_pool3d(
        jnp.asarray(rs.randn(1, 2, 6, 6, 6).astype(np.float32)),
        output_size=2, random_u=0.25)
    assert o3.shape == (1, 2, 2, 2, 2)
    with pytest.raises(ValueError, match="must not exceed"):
        F.fractional_max_pool2d(x, output_size=16)
    with pytest.raises(NotImplementedError):
        F.fractional_max_pool2d(x, output_size=2, kernel_size=3)
    with pytest.raises(NotImplementedError):
        F.fractional_max_pool2d(x, output_size=2, return_mask=True)
