"""Llama family tests: numerics, GQA, RoPE, decode-cache parity, and the
semi-auto-parallel path (BASELINE #4) — distributed step == serial step,
the reference's core oracle (SURVEY.md §4)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
from paddle_tpu.models import (LlamaConfig, LlamaForCausalLM, llama_tiny,
                               llama_shard_fn, llama_7b)
from paddle_tpu.models.llama import apply_rotary_pos_emb, _rope_tables
from paddle_tpu.nn.functional_call import functional_call, state


def test_rope_rotation_properties():
    # rotating by position 0 is identity
    x = np.random.RandomState(0).randn(2, 3, 4, 8).astype(np.float32)
    cos, sin = _rope_tables(jnp.zeros((3,)), 8, 10000.0, jnp.float32)
    out = apply_rotary_pos_emb(jnp.asarray(x), cos, sin)
    np.testing.assert_allclose(np.asarray(out), x, atol=1e-6)
    # norm-preserving at any position
    cos, sin = _rope_tables(jnp.arange(3.0) * 7, 8, 10000.0, jnp.float32)
    out = apply_rotary_pos_emb(jnp.asarray(x), cos, sin)
    np.testing.assert_allclose(np.linalg.norm(np.asarray(out), axis=-1),
                               np.linalg.norm(x, axis=-1), rtol=1e-5)


def test_rope_relative_dot_product():
    """q.k after RoPE depends only on relative distance."""
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(1, 1, 1, 16).astype(np.float32))
    k = jnp.asarray(rs.randn(1, 1, 1, 16).astype(np.float32))

    def dot_at(pq, pk):
        cq, sq = _rope_tables(jnp.asarray([float(pq)]), 16, 10000.0, jnp.float32)
        ck, sk = _rope_tables(jnp.asarray([float(pk)]), 16, 10000.0, jnp.float32)
        qq = apply_rotary_pos_emb(q, cq, sq)
        kk = apply_rotary_pos_emb(k, ck, sk)
        return float(jnp.sum(qq * kk))

    assert abs(dot_at(5, 3) - dot_at(12, 10)) < 1e-4


def test_llama_forward_shapes_gqa():
    paddle_tpu.seed(0)
    cfg = llama_tiny()
    assert cfg.kv_heads == 2 and cfg.num_heads == 4
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (2, 16)))
    logits = model(ids)
    assert logits.shape == (2, 16, 256)
    assert np.isfinite(np.asarray(logits)).all()


def test_llama_num_params_matches():
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    n = sum(int(np.prod(p.shape)) for _, p in model.named_parameters())
    assert n == cfg.num_params()


def test_llama_7b_config_size():
    # Llama-2-7B ~= 6.74B params
    n = llama_7b().num_params()
    assert 6.5e9 < n < 7.0e9, n


def test_llama_decode_cache_parity():
    paddle_tpu.seed(1)
    cfg = llama_tiny()
    cfg.dropout = 0.0
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = jnp.asarray(np.random.RandomState(2).randint(0, 256, (2, 12)))
    full = model(ids)
    caches = model.init_cache(2, 32)
    outs = []
    for t in range(12):
        lg, caches = model.decode_step(ids[:, t:t + 1], caches, t)
        outs.append(lg)
    step = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(step), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_llama_training_learns():
    paddle_tpu.seed(3)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    params, buffers = state(model)
    o = opt.AdamW(learning_rate=3e-3)
    ostate = o.init(params)
    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 256, (4, 17)))
    x, y = ids[:, :-1], ids[:, 1:]

    @jax.jit
    def step_fn(p, os_):
        def loss_fn(p):
            out, _ = functional_call(model, p, buffers, (x,))
            logp = jax.nn.log_softmax(out.astype(jnp.float32), -1)
            return -jnp.mean(jnp.take_along_axis(logp, y[..., None], -1))
        l, g = jax.value_and_grad(loss_fn)(p)
        np_, nos = o.update(g, os_, p)
        return np_, nos, l

    l0 = None
    for _ in range(30):
        params, ostate, l = step_fn(params, ostate)
        if l0 is None:
            l0 = float(l)
    assert float(l) < l0 * 0.5, (l0, float(l))


def test_llama_semi_auto_matches_serial():
    """BASELINE #4 oracle: semi-auto dp x mp step == serial step."""
    data_batches = []
    rs = np.random.RandomState(7)
    for _ in range(4):
        ids = rs.randint(0, 256, (8, 13)).astype(np.int32)
        data_batches.append((ids[:, :-1], ids[:, 1:]))

    def xent(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], -1))

    def run(shard):
        paddle_tpu.seed(5)
        cfg = llama_tiny()
        model = LlamaForCausalLM(cfg)
        mesh = None
        if shard:
            mesh = dist.ProcessMesh(np.arange(8).reshape(4, 2),
                                    dim_names=["dp", "mp"])
            dist.shard_layer(model, mesh, llama_shard_fn(mesh))
        eng = dist.Engine(model, loss=xent,
                          optimizer=opt.SGD(learning_rate=0.1),
                          process_mesh=mesh)
        return eng.fit(data_batches, epochs=2)

    serial = run(False)
    parallel = run(True)
    np.testing.assert_allclose(serial, parallel, rtol=2e-4, atol=2e-5)


def test_llama_semi_auto_param_placement():
    paddle_tpu.seed(0)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    mesh = dist.ProcessMesh(np.arange(8).reshape(4, 2), dim_names=["dp", "mp"])
    dist.shard_layer(model, mesh, llama_shard_fn(mesh))
    params = dict(model.named_parameters())
    qw = params["llama.layers.0.self_attn.q_proj.weight"]
    ow = params["llama.layers.0.self_attn.o_proj.weight"]
    assert qw.sharding.spec == P(None, "mp")
    assert ow.sharding.spec == P("mp", None)
    gw = params["llama.layers.0.mlp.gate_proj.weight"]
    assert gw.sharding.spec == P(None, "mp")


def test_llama_chunked_prefill_parity():
    # multi-token prefill via decode_step (s>1 with cache) must stay causal
    # WITHIN the chunk (ADVICE r1: broadcast mask let queries see later
    # tokens of the same chunk)
    paddle_tpu.seed(5)
    cfg = llama_tiny()
    cfg.dropout = 0.0
    model = LlamaForCausalLM(cfg)
    model.eval()
    ids = jnp.asarray(np.random.RandomState(9).randint(0, 256, (2, 12)))
    full = model(ids)
    caches = model.init_cache(2, 32)
    outs = []
    for lo, hi in [(0, 5), (5, 8), (8, 12)]:   # uneven chunks
        lg, caches = model.decode_step(ids[:, lo:hi], caches, lo)
        outs.append(lg)
    step = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(step), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def test_llama_matches_transformers_weight_mapped():
    """Architectural exactness vs a weight-mapped transformers.LlamaModel
    (config-only, GQA, no network) — same oracle pattern as BERT."""
    import torch
    from transformers import LlamaConfig as HFConfig, LlamaModel as HFModel
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny

    hf_cfg = HFConfig(vocab_size=256, hidden_size=64,
                      intermediate_size=176, num_hidden_layers=2,
                      num_attention_heads=4, num_key_value_heads=2,
                      max_position_embeddings=128, rms_norm_eps=1e-5,
                      rope_theta=10000.0, attention_bias=False,
                      mlp_bias=False, tie_word_embeddings=True)
    torch.manual_seed(0)
    hf = HFModel(hf_cfg).eval()

    paddle_tpu.seed(0)
    mine = LlamaForCausalLM(llama_tiny())
    mine.eval()

    # map straight into the BACKBONE's parameter dict (no prefix games)
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    mapped, _ = state(mine.llama)
    mapped = dict(mapped)
    mapped["embed_tokens.weight"] = jnp.asarray(sd["embed_tokens.weight"])
    mapped["norm.weight"] = jnp.asarray(sd["norm.weight"])
    for i in range(2):
        for name in ("q_proj", "k_proj", "v_proj", "o_proj"):
            mapped[f"layers.{i}.self_attn.{name}.weight"] = \
                jnp.asarray(sd[f"layers.{i}.self_attn.{name}.weight"].T)
        for name in ("gate_proj", "up_proj", "down_proj"):
            mapped[f"layers.{i}.mlp.{name}.weight"] = \
                jnp.asarray(sd[f"layers.{i}.mlp.{name}.weight"].T)
        for name in ("input_layernorm", "post_attention_layernorm"):
            mapped[f"layers.{i}.{name}.weight"] = \
                jnp.asarray(sd[f"layers.{i}.{name}.weight"])

    ids = np.random.RandomState(3).randint(0, 256, (2, 12))
    with torch.no_grad():
        ref = hf(input_ids=torch.tensor(ids)).last_hidden_state.numpy()

    hidden, _ = functional_call(mine.llama, mapped, {},
                                (jnp.asarray(ids),), train=False)
    np.testing.assert_allclose(np.asarray(hidden), ref, rtol=2e-4,
                               atol=2e-4)
