"""Tensor-parallel serving (serving/tp.py + kernels/collective_matmul).

The load-bearing contracts:

  * TOKEN-FOR-TOKEN parity between a tp=1 engine and tp in {2, 4, 8}
    engines on mixed-length workloads, greedy AND seeded sampling, GPT
    (MHA, learned positions, tied head) and Llama (GQA, rotary, SwiGLU,
    untied head) — the TP decode is the same arithmetic re-partitioned,
    so exact equality is the bar;
  * the fused compute-collective primitives (ring-decomposed
    allgather_matmul / matmul_reduce_scatter) match their serialized
    collective forms and the dense single-device reference;
  * the compile-count pin survives the mesh: {chunk} + pow2 buckets +
    ONE decode + ONE gather + ONE scatter per plane, at any tp;
  * the fallback matrix: the Pallas decode-block leg under TP is
    legality-gated (ISSUE 12 — ``tp_fused_block`` engages at legal
    shapes, tests/test_zz_decode_block_tp.py holds its parity matrix);
    an unsupported shape (num_slots not divisible) falls back to the
    composed GSPMD decode and KEEPS SERVING with parity.

zz-prefixed for the same reason as test_zz_decode_block /
test_zz_bench_projection: this file drives shard_map + ppermute rings on
the 8-device CPU mesh, and the jaxlib-0.4 dispatch-race window conftest
documents makes early-alphabet placement of distributed files
reproducibly fragile — sort after the window.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu
from paddle_tpu.models import (GPTForCausalLM, LlamaForCausalLM,
                               gpt_tiny, llama_tiny)
from paddle_tpu.serving import SamplingParams, ServingEngine
from paddle_tpu.serving.tp import build_serving_mesh

LENGTHS = (5, 11, 3, 17, 30)
NEW = 6


def _prompts(seed=0, lengths=LENGTHS, vocab=256):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, vocab, (L,)) for L in lengths]


def _fresh(maker, seed=0):
    """Deterministic model build: TP engines shard the weights in
    place, so every engine gets its own identically-initialized model."""
    paddle_tpu.seed(seed)
    m = maker()
    m.eval()
    return m


def _serve(model, tp, sampling=None, **kw):
    eng = ServingEngine(model, num_slots=4, tensor_parallel=tp, **kw)
    outs = eng.serve_batch(_prompts(), max_new_tokens=NEW,
                           sampling=sampling, max_steps=2000)
    assert all(o.finished for o in outs)
    return [o.tokens for o in outs], eng


SAMPLED = SamplingParams(do_sample=True, temperature=0.9, top_k=12,
                         top_p=0.85, seed=7)


# -------------------------------------------- collective-matmul kernels

def test_collective_matmul_parity():
    """Ring-overlapped == serialized collective == dense reference, for
    both the entry (allgather@dot) and exit (dot@reduce-scatter)
    primitives, on a real 4-device mesh."""
    from paddle_tpu.distributed._jax_compat import shard_map
    from paddle_tpu.kernels.collective_matmul import (
        allgather_matmul, matmul_reduce_scatter)
    tp = 4
    mesh = build_serving_mesh(tp)
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(8, 16), jnp.float32)       # [B, K]
    w_col = jnp.asarray(rs.randn(16, 12), jnp.float32)  # K x N (col-sh)
    w_row = jnp.asarray(rs.randn(16, 12), jnp.float32)  # K (row-sh) x N

    def ag(overlap):
        def body(xs, w):
            return allgather_matmul(xs, w, "mp", tp, overlap=overlap)
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P("mp", None), P(None, "mp")),
            out_specs=P(None, "mp"), check_vma=False))(x, w_col)

    dense = x @ w_col
    np.testing.assert_allclose(np.asarray(ag(True)), np.asarray(dense),
                               rtol=1e-6, atol=1e-6)
    np.testing.assert_array_equal(np.asarray(ag(True)),
                                  np.asarray(ag(False)))

    def rs_(overlap):
        def body(xs, w):
            return matmul_reduce_scatter(xs, w, "mp", tp,
                                         overlap=overlap)
        return jax.jit(shard_map(
            body, mesh=mesh, in_specs=(P(None, "mp"), P("mp", None)),
            out_specs=P("mp", None), check_vma=False))(x, w_row)

    dense2 = x @ w_row
    np.testing.assert_allclose(np.asarray(rs_(True)),
                               np.asarray(dense2), rtol=1e-5, atol=1e-5)
    # ring chain vs psum tree reduce in different orders: allclose, not
    # bit-equal, is the contract between the two collective forms
    np.testing.assert_allclose(np.asarray(rs_(True)),
                               np.asarray(rs_(False)),
                               rtol=1e-5, atol=1e-5)


def test_build_serving_mesh_validation():
    with pytest.raises(ValueError, match="tensor_parallel"):
        build_serving_mesh(0)
    with pytest.raises(ValueError, match="devices"):
        build_serving_mesh(len(jax.devices()) + 1)


# ------------------------------------------------------- GPT parity

def test_gpt_tp_greedy_parity():
    base, e1 = _serve(_fresh(lambda: GPTForCausalLM(gpt_tiny())), 1)
    assert e1.decode_path == "unfused"
    for tp in (2, 4):
        toks, eng = _serve(
            _fresh(lambda: GPTForCausalLM(gpt_tiny())), tp)
        assert eng.decode_path == "tp_fused"
        assert eng.tp_fusion_reason is None
        assert toks == base
        assert eng.tensor_parallel == tp


def test_gpt_tp8_fused_parity():
    """Degree 8 — the deepest ring the 8-device mesh allows (7 ppermute
    hops per fused collective): the tp_fused program itself, not the
    GSPMD fallback, must hold token parity.  gpt_tiny has 4 heads, so
    this uses an 8-head tiny config with num_slots=8 (both must tile
    the mesh for the fused path to engage)."""
    from paddle_tpu.models import GPTConfig
    mk = lambda: GPTForCausalLM(GPTConfig(
        vocab_size=256, hidden_size=64, num_layers=2, num_heads=8,
        max_seq_len=128))

    def serve(tp):
        m = _fresh(mk)
        eng = ServingEngine(m, num_slots=8, tensor_parallel=tp)
        outs = eng.serve_batch(_prompts(), max_new_tokens=NEW,
                               max_steps=2000)
        assert all(o.finished for o in outs)
        return [o.tokens for o in outs], eng

    base, _ = serve(1)
    toks, eng = serve(8)
    assert eng.decode_path == "tp_fused"
    assert eng.tp_fusion_reason is None
    assert toks == base


def test_gpt_tp4_seeded_sampling_parity():
    base, _ = _serve(_fresh(lambda: GPTForCausalLM(gpt_tiny())), 1,
                     sampling=SAMPLED)
    toks, eng = _serve(_fresh(lambda: GPTForCausalLM(gpt_tiny())), 4,
                       sampling=SAMPLED)
    assert eng.decode_path == "tp_fused"
    assert toks == base


def test_gpt_tp2_gspmd_fallback_parity():
    """collective_fusion=False: the composed decode runs as a
    GSPMD-partitioned program over the mesh — same tokens, explicit
    fallback reason."""
    base, _ = _serve(_fresh(lambda: GPTForCausalLM(gpt_tiny())), 1)
    toks, eng = _serve(_fresh(lambda: GPTForCausalLM(gpt_tiny())), 2,
                       collective_fusion=False)
    assert eng.decode_path == "unfused"
    assert "collective_fusion" in eng.tp_fusion_reason
    assert toks == base


# ------------------------------------------------------ Llama parity

def test_llama_tp2_parity_greedy_and_sampled():
    mk = lambda: LlamaForCausalLM(llama_tiny())
    base_g, _ = _serve(_fresh(mk), 1)
    base_s, _ = _serve(_fresh(mk), 1, sampling=SAMPLED)
    toks_g, eng = _serve(_fresh(mk), 2)
    assert eng.decode_path == "tp_fused"     # GQA: kv_heads=2 tiles tp=2
    assert toks_g == base_g
    toks_s, _ = _serve(_fresh(mk), 2, sampling=SAMPLED)
    assert toks_s == base_s


def test_llama_tp4_rejects_on_kv_heads():
    """kv_heads=2 cannot partition over 4 devices: the slot slabs shard
    on the kv-head axis, so construction is a loud error, not silent
    replication — and it fires BEFORE the model is resharded, so a
    caller that catches and retries at tp=1 gets an untouched
    single-device model."""
    m = _fresh(lambda: LlamaForCausalLM(llama_tiny()))
    before = m.lm_head.weight.sharding
    with pytest.raises(ValueError, match="kv_heads"):
        ServingEngine(m, num_slots=4, tensor_parallel=4)
    assert m.lm_head.weight.sharding == before
    # ...and the untouched model still serves single-chip
    outs = ServingEngine(m, num_slots=2).serve_batch(
        _prompts(lengths=(4,)), max_new_tokens=2)
    assert outs[0].finished


# ----------------------------------------------- fallback matrix / pin

def test_pallas_fused_decode_conditional_under_tp():
    """fused_decode=True on a TP mesh (ISSUE 12): the hard
    "tensor_parallel" refusal is gone — at a legal shape the resolve
    chain ACCEPTS and the engine decodes through the sharded Pallas
    block (``tp_fused_block``) with token parity; an ILLEGAL shape
    (kv-heads not tiling the mesh is checked at construction, so probe
    the resolver directly) refuses with the real legality reason and
    the engine keeps serving on the next rung."""
    from paddle_tpu.kernels.decode_block import resolve_fused_decode
    m = _fresh(lambda: GPTForCausalLM(gpt_tiny()))
    ok, reason = resolve_fused_decode(m, batch=4, kv_len=128, tp=2)
    assert (ok, reason) == (True, None)
    toks, eng = _serve(m, 2, fused_decode=True)
    assert eng.decode_path == "tp_fused_block"
    assert eng.decode_fallback_reason is None
    base, _ = _serve(_fresh(lambda: GPTForCausalLM(gpt_tiny())), 1)
    assert toks == base
    # illegal: batch 3 cannot slot-shard over 2 devices — refusal names
    # the real check, and the engine's chain lands on the composed
    # compute-collective program... which ALSO refuses at num_slots=3,
    # so the GSPMD decode serves (the chain's last rung)
    ok, reason = resolve_fused_decode(m, batch=3, kv_len=128, tp=2)
    assert not ok and "batch 3" in reason
    m2 = _fresh(lambda: GPTForCausalLM(gpt_tiny()))
    eng2 = ServingEngine(m2, num_slots=3, tensor_parallel=2,
                         fused_decode=True)
    assert eng2.decode_path == "unfused"
    assert "batch 3" in eng2.decode_fallback_reason
    outs = eng2.serve_batch(_prompts(lengths=(4, 9)), max_new_tokens=4)
    assert all(o.finished for o in outs)


def test_tp_unsupported_shape_falls_back_and_serves():
    """num_slots=3 does not tile tp=2 — the fused program needs the
    residual stream slot-sharded, so the engine falls back to the
    composed GSPMD decode with an explicit reason and still serves."""
    m = _fresh(lambda: GPTForCausalLM(gpt_tiny()))
    eng = ServingEngine(m, num_slots=3, tensor_parallel=2)
    assert eng.decode_path == "unfused"
    assert "num_slots" in eng.tp_fusion_reason
    outs = eng.serve_batch(_prompts(lengths=(4, 9)), max_new_tokens=4)
    assert all(o.finished for o in outs)


def test_compile_count_pin_under_tp():
    """The mesh must not change the compiled-program SET: mixed lengths
    + cache hits + chunked prefill at tp=4 still lower {chunk} + pow2
    tails, ONE decode, ONE block gather, ONE block scatter."""
    m = _fresh(lambda: GPTForCausalLM(gpt_tiny()))
    eng = ServingEngine(m, num_slots=4, min_bucket=8, prefill_chunk=16,
                        block_len=16, tensor_parallel=4)
    prompts = _prompts(1, (3, 9, 17, 33, 50))
    rids = [eng.submit(p, max_new_tokens=3) for p in prompts]
    eng.run_until_complete(500)
    rids.append(eng.submit(prompts[-1].copy(), max_new_tokens=3))
    eng.run_until_complete(100)
    assert all(eng.result(r).finished for r in rids)
    assert eng.result(rids[-1]).prefix_hit_tokens == 48
    core = eng.core
    assert core.trace_counts["decode"] == 1
    assert core.trace_counts["prefill"] == 2       # 16 (chunk) + 8
    assert core.block_pool.trace_counts == {"gather": 1, "scatter": 1}


# -------------------------------------------------- telemetry / layout

def test_tp_metrics_and_sharded_plane():
    m = _fresh(lambda: GPTForCausalLM(gpt_tiny()))
    eng = ServingEngine(m, num_slots=4, tensor_parallel=2)
    outs = eng.serve_batch(_prompts(lengths=(4, 9)), max_new_tokens=4)
    assert all(o.finished for o in outs)
    snap = eng.registry.snapshot()
    assert snap["serving.tp_degree"] == 2
    coll = snap["serving.collective_s"]
    assert coll["count"] > 0 and coll["sum"] > 0
    # the degree is an engine-lifetime constant: the warmup->reset->
    # measure flow must not zero it (nothing re-publishes it per step)
    eng.metrics.reset()
    assert eng.registry.snapshot()["serving.tp_degree"] == 2
    # the device plane is genuinely sharded: slabs on the kv-head axis
    spec = eng.core.pool.ks[0].sharding.spec
    assert tuple(spec) == (None, None, "mp", None)
    spec_b = eng.core.block_pool.bks[0].sharding.spec
    assert tuple(spec_b) == (None, None, "mp", None)
    # single-chip engines report degree 1 and record no collectives
    m1 = _fresh(lambda: GPTForCausalLM(gpt_tiny()))
    e1 = ServingEngine(m1, num_slots=2)
    e1.serve_batch(_prompts(lengths=(4,)), max_new_tokens=2)
    snap1 = e1.registry.snapshot()
    assert snap1["serving.tp_degree"] == 1
    assert snap1["serving.collective_s"]["count"] == 0


def test_multichip_serving_smoke_artifacts(tmp_path):
    """Tier-1 artifact smoke (mirrors test_chaos_smoke_artifacts): the
    multi-chip serving CI script end-to-end on the virtual-device mesh —
    per-degree parity verdict + the scraped tp gauge/collective
    histogram."""
    import importlib.util
    import json
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    spec = importlib.util.spec_from_file_location(
        "multichip_serving_smoke",
        os.path.join(repo, "scripts", "multichip_serving_smoke.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    out = str(tmp_path / "artifacts")
    assert mod.main(["--out", out, "--degrees", "1,2,4",
                     "--requests", "4", "--new", "4"]) == 0
    with open(os.path.join(out, "serving_tp.json")) as f:
        v = json.load(f)
    assert v["ok"]
    # ISSUE 12: both modes run — composed (tp_fused at tp > 1) and
    # fused (the sharded Pallas block, tp_fused_block), with CROSS-mode
    # token parity against the composed tp=1 baseline
    assert [(r["mode"], r["tp"]) for r in v["rows"]] == \
        [("composed", 1), ("composed", 2), ("composed", 4),
         ("fused", 1), ("fused", 2), ("fused", 4)]
    for r in v["rows"]:
        assert r["parity_vs_tp1"] and r["drained"] and r["path_ok"]
        if r["tp"] > 1:
            assert r["plane_sharded"]
            assert r["decode_path"] == ("tp_fused_block"
                                        if r["mode"] == "fused"
                                        else "tp_fused")
            assert r["collective_s"]["count"] > 0
    prom = open(os.path.join(out, "metrics.prom")).read()
    assert "serving_tp_degree" in prom
    assert "serving_collective_s" in prom


def test_serving_tp_bench_row_smoke():
    """The bench's serving_tp_scaling row runs on the virtual-device
    mesh and carries the schema the scaling story is read from."""
    import bench
    row = bench._serving_tp_bench(smoke=True)
    assert row["rows"], row
    degrees = [r["tp"] for r in row["rows"]]
    assert degrees[0] == 1 and len(degrees) >= 2
    for r in row["rows"]:
        assert r["tokens_per_sec"] is not None
        assert "ttft_p50_ms" in r and "ttft_p99_ms" in r
        assert r["parity_vs_tp1"] is True
        assert 0 < r["scaling_efficiency"] or r["tp"] == 1
        # ISSUE 20: tp>1 rows quote the statically-proved per-hop ring
        # payload from the graftcomm seam manifest next to the measured
        # collective latency
        if r["tp"] > 1:
            assert r["comm_note"] and "B/hop" in r["comm_note"], r
            assert "graftcomm" in r["comm_note"]
        else:
            assert r["comm_note"] is None
    assert row["collective_fusion"]["max_abs_diff"] < 1e-4
