"""jit.to_static / jit.save / jit.load / inference predictor tests
(reference: paddle.jit.save+load round-trip and AnalysisPredictor smoke —
SURVEY.md §1 L9, §3.5; VERDICT r1 missing item: export path)."""

import json
import os
import subprocess
import sys

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from conftest import requires_modern_jax

import paddle_tpu
import paddle_tpu.nn as nn
from paddle_tpu.jit import to_static, save, load, StaticFunction
from paddle_tpu.static import InputSpec
from paddle_tpu.nn.functional_call import state


class SmallNet(nn.Layer):
    def __init__(self, d=8, h=16):
        super().__init__()
        self.fc1 = nn.Linear(d, h)
        self.fc2 = nn.Linear(h, 4)

    def forward(self, x):
        return self.fc2(jnp.tanh(self.fc1(x)))


def test_to_static_matches_eager():
    paddle_tpu.seed(0)
    net = SmallNet()
    net.eval()
    x = jnp.asarray(np.random.RandomState(0).randn(3, 8), jnp.float32)
    eager = net(x)
    st = to_static(net)
    assert isinstance(st, StaticFunction)
    np.testing.assert_allclose(np.asarray(st(x)), np.asarray(eager),
                               rtol=1e-6, atol=1e-6)
    # decorator form on a plain function
    @to_static
    def f(a):
        return jnp.sin(a) * 2
    np.testing.assert_allclose(np.asarray(f(x)), np.asarray(jnp.sin(x) * 2),
                               rtol=1e-6)


def test_save_load_roundtrip_same_process(tmp_path):
    paddle_tpu.seed(1)
    net = SmallNet()
    net.eval()
    x = jnp.asarray(np.random.RandomState(1).randn(5, 8), jnp.float32)
    ref = np.asarray(net(x))
    prefix = str(tmp_path / "model")
    save(net, prefix, input_spec=[InputSpec([None, 8], "float32")])
    assert os.path.exists(prefix + ".pdmodel")
    assert os.path.exists(prefix + ".pdiparams.npz")
    loaded = load(prefix)
    got = np.asarray(loaded(x))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)
    # dynamic batch: a different batch size runs through the same artifact
    x2 = jnp.asarray(np.random.RandomState(2).randn(9, 8), jnp.float32)
    np.testing.assert_allclose(np.asarray(loaded(x2)), np.asarray(net(x2)),
                               rtol=1e-6, atol=1e-6)


def test_save_load_fresh_process(tmp_path):
    """The VERDICT's oracle: train -> save -> FRESH process load -> same
    logits (no Python model class available in the loader)."""
    paddle_tpu.seed(2)
    net = SmallNet()
    net.eval()
    x = np.random.RandomState(3).randn(4, 8).astype(np.float32)
    ref = np.asarray(net(jnp.asarray(x)))
    prefix = str(tmp_path / "m")
    save(net, prefix, input_spec=[InputSpec([None, 8], "float32")])
    np.save(str(tmp_path / "x.npy"), x)

    code = f"""
import jax
jax.config.update("jax_platforms", "cpu")
import jax.extend.backend as jeb
jeb.clear_backends()
import sys, numpy as np
sys.path.insert(0, {os.path.dirname(os.path.dirname(os.path.abspath(__file__)))!r})
from paddle_tpu.jit import load
m = load({prefix!r})
x = np.load({str(tmp_path / 'x.npy')!r})
out = np.asarray(m(x))
np.save({str(tmp_path / 'out.npy')!r}, out)
print("OK")
"""
    r = subprocess.run([sys.executable, "-c", code], capture_output=True,
                       text=True, timeout=240)
    assert "OK" in r.stdout, r.stderr[-800:]
    got = np.load(str(tmp_path / "out.npy"))
    np.testing.assert_allclose(got, ref, rtol=1e-6, atol=1e-6)


def test_inference_predictor(tmp_path):
    from paddle_tpu.inference import Config, create_predictor
    paddle_tpu.seed(4)
    net = SmallNet()
    net.eval()
    prefix = str(tmp_path / "pred")
    save(net, prefix, input_spec=[InputSpec([None, 8], "float32",
                                            name="input")])
    cfg = Config(prefix + ".pdmodel", prefix + ".pdiparams")
    pred = create_predictor(cfg)
    names = pred.get_input_names()
    assert names == ["input"]
    x = np.random.RandomState(5).randn(2, 8).astype(np.float32)
    pred.get_input_handle(names[0]).copy_from_cpu(x)
    assert pred.run()
    out_names = pred.get_output_names()
    out = pred.get_output_handle(out_names[0]).copy_to_cpu()
    np.testing.assert_allclose(out, np.asarray(net(jnp.asarray(x))),
                               rtol=1e-6, atol=1e-6)


def test_inference_config_knobs_warn_once(recwarn):
    """VERDICT r3 weak 6: GPU/TRT-era knobs must warn (once per process)
    that the XLA path ignores them, not silently no-op."""
    import warnings
    from paddle_tpu import inference as inf
    inf._WARNED_KNOBS.clear()
    cfg = inf.Config("m")
    with warnings.catch_warnings(record=True) as ws:
        warnings.simplefilter("always")
        cfg.enable_use_gpu(256, 0)
        cfg.enable_tensorrt_engine(workspace_size=1 << 20)
        cfg.enable_use_gpu()          # repeat: no second warning
        cfg.switch_ir_optim(False)
    msgs = [str(w.message) for w in ws]
    assert sum("enable_use_gpu" in m for m in msgs) == 1
    assert sum("enable_tensorrt_engine" in m for m in msgs) == 1
    assert sum("switch_ir_optim" in m for m in msgs) == 1
    assert all("no effect on the XLA/TPU path" in m for m in msgs)


def test_static_save_load_inference_model(tmp_path):
    import paddle_tpu.static as static
    paddle_tpu.seed(5)
    net = SmallNet()
    net.eval()
    prefix = str(tmp_path / "im")
    static.save_inference_model(prefix, [InputSpec([None, 8], "float32")],
                                net)
    m = static.load_inference_model(prefix)
    x = jnp.asarray(np.random.RandomState(6).randn(3, 8), jnp.float32)
    np.testing.assert_allclose(np.asarray(m(x)), np.asarray(net(x)),
                               rtol=1e-6, atol=1e-6)


@requires_modern_jax
def test_save_load_multi_device_program(tmp_path):
    """AOT export of the FULL hybrid-parallel train step (dp2 x mp2 x pp2
    over 8 devices): serialize, reload, execute — bit-equal loss.  The
    deployment story for distributed programs (round-3 addition)."""
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as popt
    from paddle_tpu.models import gpt_tiny, GPTHybridTrainer
    from paddle_tpu import jit as pjit

    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    dist.fleet.init(is_collective=True, strategy=s)
    try:
        paddle_tpu.seed(0)
        tr = GPTHybridTrainer(gpt_tiny(remat=False),
                              dist.get_hybrid_communicate_group(),
                              popt.SGD(learning_rate=0.1), microbatches=2)
        state = tr.init_state()
        x, y = tr.make_batch(batch=4, seq=16)
        step = tr.jit_step(donate=False)
        lr = jnp.asarray(0.1, jnp.float32)
        want = step(*state, x, y, lr)

        path = str(tmp_path / "hybrid_step")
        exp = pjit.save_program(step, path, *state, x, y, lr)
        assert exp.nr_devices == 8

        back = pjit.load_program(path)
        got = back.call(*state, x, y, lr)
        np.testing.assert_allclose(np.asarray(got[-1]),
                                   np.asarray(want[-1]), rtol=1e-6)
        # updated params match too (spot check one leaf)
        k = next(iter(want[0]))
        np.testing.assert_allclose(np.asarray(got[0][k]),
                                   np.asarray(want[0][k]), rtol=1e-6)
    finally:
        dist.topology.set_hybrid_communicate_group(None)
