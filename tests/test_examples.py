"""The examples/ scripts are living documentation — run each end-to-end
at tiny settings so they cannot rot (subprocess, scrubbed TPU plugin,
8-device CPU mesh)."""

import os
import subprocess
import sys

import pytest

from conftest import requires_modern_jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(script, *args, timeout=600):
    env = {k: v for k, v in os.environ.items()}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/paddle_tpu_jax_cache")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "examples", script), *args],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, (script, r.stdout[-800:], r.stderr[-800:])
    # the fleet examples log through the rank-tagged logger (stderr)
    return r.stdout + r.stderr


def test_example_deploy_generate():
    out = _run("deploy_generate.py", "--steps", "60")
    assert "quantized" in out
    assert "AOT artifact reloaded, tokens bit-equal" in out


def test_example_train_gnn():
    out = _run("train_gnn.py", "--steps", "25", "--nodes", "128",
               "--edges", "1024", "--hidden", "32")
    assert "train accuracy" in out
    assert "sampled-subgraph forward" in out


@requires_modern_jax
def test_example_train_gpt_hybrid():
    out = _run("train_gpt_hybrid.py", "--dp", "1", "--mp", "2", "--pp", "2",
               "--steps", "3", "--batch", "4", "--seq", "32")
    assert "loss" in out.lower(), out[-400:]


def test_example_train_llama_semi_auto():
    out = _run("train_llama_semi_auto.py", "--dp", "2", "--mp", "2",
               "--steps", "3", "--batch", "4", "--seq", "32")
    assert "loss" in out.lower(), out[-400:]


@requires_modern_jax
def test_example_train_moe_ep():
    out = _run("train_moe_ep.py", "--ep", "2", "--pp", "2", "--sharding",
               "1", "--steps", "2", "--batch", "4", "--seq", "16")
    assert "OK: expert-parallel MoE trained" in out, out[-400:]


def test_example_train_static():
    out = _run("train_static.py", "--steps", "60")
    assert "STATIC_EXAMPLE_OK" in out


def test_example_train_sparse_pointcloud():
    out = _run("train_sparse_pointcloud.py", "--steps", "120")
    assert "SPARSE_POINTCLOUD_OK" in out


def test_example_infer_export():
    out = _run("infer_export.py")
    low = out.lower()
    assert "export" in low or "predict" in low or "ok" in low, out[-400:]


def test_example_train_detection():
    out = _run("train_detection.py", "--steps", "150")
    # the example enforces its own localization/class thresholds
    assert "localized" in out
    assert "OK" in out
