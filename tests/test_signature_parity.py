"""Signature parity: ported code calls these APIs with KEYWORD arguments,
so parameter names and order are part of the contract (the reference's
signatures are YAML-generated and stable).  Leading-parameter audit over
the most-called surfaces; extend when a porting report names a new one.
"""

import inspect

import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F


CHECKS = [
    (nn.Conv2D, ["in_channels", "out_channels", "kernel_size", "stride",
                 "padding", "dilation", "groups", "padding_mode",
                 "weight_attr", "bias_attr", "data_format"]),
    (nn.Linear, ["in_features", "out_features", "weight_attr", "bias_attr"]),
    (nn.BatchNorm2D, ["num_features", "momentum", "epsilon"]),
    (nn.LayerNorm, ["normalized_shape", "epsilon"]),
    (nn.Embedding, ["num_embeddings", "embedding_dim", "padding_idx",
                    "sparse"]),
    (nn.MultiHeadAttention, ["embed_dim", "num_heads", "dropout"]),
    (nn.TransformerEncoderLayer, ["d_model", "nhead", "dim_feedforward",
                                  "dropout", "activation"]),
    (nn.LSTM, ["input_size", "hidden_size", "num_layers", "direction"]),
    (nn.GRU, ["input_size", "hidden_size", "num_layers"]),
    (F.conv2d, ["x", "weight", "bias", "stride", "padding", "dilation",
                "groups", "data_format"]),
    (F.linear, ["x", "weight", "bias"]),
    (F.softmax, ["x", "axis"]),
    (F.cross_entropy, ["input", "label", "weight", "ignore_index",
                       "reduction", "soft_label", "axis"]),
    (F.dropout, ["x", "p", "axis", "training", "mode"]),
    (F.layer_norm, ["x", "normalized_shape", "weight", "bias", "epsilon"]),
    (F.max_pool2d, ["x", "kernel_size", "stride", "padding"]),
    (F.interpolate, ["x", "size", "scale_factor", "mode", "align_corners"]),
    (F.scaled_dot_product_attention, ["query", "key", "value", "attn_mask",
                                      "dropout_p", "is_causal"]),
    (paddle.matmul, ["x", "y", "transpose_x", "transpose_y"]),
    (paddle.concat, ["x", "axis"]),
    (paddle.split, ["x", "num_or_sections", "axis"]),
    (paddle.reshape, ["x", "shape"]),
    (paddle.topk, ["x", "k", "axis", "largest", "sorted"]),
    (paddle.arange, ["start", "end", "step", "dtype"]),
    (paddle.full, ["shape", "fill_value", "dtype"]),
    (paddle.optimizer.AdamW, ["learning_rate", "beta1", "beta2", "epsilon",
                              "parameters", "weight_decay"]),
    (paddle.optimizer.Momentum, ["learning_rate", "momentum", "parameters"]),
    (paddle.io.DataLoader, ["dataset", "feed_list", "places",
                            "return_list", "batch_sampler", "batch_size",
                            "shuffle", "drop_last", "collate_fn",
                            "num_workers"]),
    (paddle.distributed.all_reduce, ["tensor", "op", "group"]),
    (paddle.distributed.all_gather, ["tensor_list", "tensor", "group"]),
]


@pytest.mark.parametrize(
    "fn,expected", CHECKS,
    ids=[getattr(fn, "__name__", str(fn)) for fn, _ in CHECKS])
def test_leading_parameters_match_reference(fn, expected):
    target = fn.__init__ if inspect.isclass(fn) else fn
    sig = list(inspect.signature(target).parameters)
    if sig and sig[0] == "self":
        sig = sig[1:]
    assert sig[:len(expected)] == expected, (
        f"{getattr(fn, '__name__', fn)}: leading params {sig[:len(expected)]}"
        f" != reference {expected}")


def test_all_gather_keyword_call_form():
    # the reference's list-output keyword spelling must work verbatim
    out = []
    res = paddle.distributed.all_gather(tensor_list=out,
                                        tensor=jnp.ones((2,)))
    assert res is out and len(out) >= 1


DEFAULT_CHECKS = [
    (F.dropout, {"p": 0.5, "mode": "upscale_in_train"}),
    (F.leaky_relu, {"negative_slope": 0.01}),
    (F.softmax, {"axis": -1}),
    (F.cross_entropy, {"reduction": "mean", "ignore_index": -100,
                       "soft_label": False}),
    (F.interpolate, {"mode": "nearest", "align_corners": False}),
    (F.gelu, {"approximate": False}),
    (nn.BatchNorm2D.__init__, {"momentum": 0.9, "epsilon": 1e-5}),
    (nn.LayerNorm.__init__, {"epsilon": 1e-5}),
    (nn.Dropout.__init__, {"p": 0.5}),
    (paddle.optimizer.Adam.__init__, {"learning_rate": 0.001, "beta1": 0.9,
                                      "beta2": 0.999, "epsilon": 1e-8}),
    (paddle.optimizer.AdamW.__init__, {"learning_rate": 0.001,
                                       "weight_decay": 0.01}),
    (paddle.optimizer.Momentum.__init__, {"learning_rate": 0.001,
                                          "momentum": 0.9,
                                          "use_nesterov": False}),
    (paddle.topk, {"largest": True, "sorted": True}),
    (paddle.argsort, {"axis": -1, "descending": False}),
    # reference: p=None selects fro (matrix) / 2-norm (vector)
    (paddle.norm, {"p": None}),
    (paddle.matmul, {"transpose_x": False, "transpose_y": False}),
    (nn.MultiHeadAttention.__init__, {"dropout": 0.0}),
    (nn.TransformerEncoderLayer.__init__, {"dropout": 0.1,
                                           "activation": "relu"}),
]


@pytest.mark.parametrize(
    "fn,want", DEFAULT_CHECKS,
    ids=[fn.__qualname__ for fn, _ in DEFAULT_CHECKS])
def test_default_values_match_reference(fn, want):
    sig = inspect.signature(fn)
    for k, v in want.items():
        assert k in sig.parameters, f"{fn.__qualname__} lost param {k}"
        assert sig.parameters[k].default == v, (
            f"{fn.__qualname__}.{k} default "
            f"{sig.parameters[k].default!r} != reference {v!r}")


def test_transformer_encoder_dim_feedforward_required():
    # the reference REQUIRES dim_feedforward (torch defaults it; ported
    # paddle code always passes it, torch-ported code must adapt loudly)
    p = inspect.signature(
        nn.TransformerEncoderLayer.__init__).parameters["dim_feedforward"]
    assert p.default is inspect.Parameter.empty
