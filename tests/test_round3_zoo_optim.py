"""Round-3 zoo/optimizer completions: torch-oracle optimizer checks,
LBFGS convergence, model-family forward shapes + train smoke."""

import numpy as np
import pytest
import torch

import jax
import jax.numpy as jnp

import paddle_tpu
import paddle_tpu.optimizer as opt

rs = np.random.RandomState(0)


def _run_opt(mine_cls, torch_cls, steps=5, **kw):
    """Apply both optimizers to the same quadratic; compare trajectories."""
    w0 = rs.randn(6).astype(np.float32)
    target = rs.randn(6).astype(np.float32)

    o = mine_cls(learning_rate=0.05, **kw.get("mine", {}))
    p = {"w": jnp.asarray(w0)}
    st = o.init(p)
    for _ in range(steps):
        g = {"w": 2.0 * (p["w"] - jnp.asarray(target))}
        p, st = o.update(g, st, p)

    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    to = torch_cls([tw], lr=0.05, **kw.get("torch", {}))
    for _ in range(steps):
        to.zero_grad()
        loss = ((tw - torch.tensor(target)) ** 2).sum()
        loss.backward()
        to.step()
    np.testing.assert_allclose(np.asarray(p["w"]), tw.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_nadam_matches_torch():
    _run_opt(opt.NAdam, torch.optim.NAdam)


def test_radam_matches_torch():
    # include steps beyond the rectification warmup threshold
    _run_opt(opt.RAdam, torch.optim.RAdam, steps=8)


def test_rprop_matches_torch():
    _run_opt(opt.Rprop, torch.optim.Rprop,
             mine={"learning_rate_range": (1e-6, 50.0)},
             torch={"step_sizes": (1e-6, 50.0)})


def test_lbfgs_converges_on_quadratic():
    A = rs.randn(8, 8).astype(np.float32)
    A = A @ A.T + 0.5 * np.eye(8, dtype=np.float32)  # SPD
    b = rs.randn(8).astype(np.float32)

    def loss_fn(p):
        w = p["w"]
        return 0.5 * w @ jnp.asarray(A) @ w - jnp.asarray(b) @ w

    o = opt.LBFGS(learning_rate=1.0, max_iter=50,
                  line_search_fn="strong_wolfe")
    p, loss = o.step(loss_fn, {"w": jnp.zeros(8)})
    w_star = np.linalg.solve(A, b)
    np.testing.assert_allclose(np.asarray(p["w"]), w_star, rtol=1e-3,
                               atol=1e-3)


def test_multiplicative_decay():
    sch = opt.lr.MultiplicativeDecay(1.0, lambda t: 0.5)
    vals = []
    for _ in range(4):
        vals.append(float(sch.get_lr()))
        sch.step()
    np.testing.assert_allclose(vals, [1.0, 0.5, 0.25, 0.125], rtol=1e-6)


@pytest.mark.parametrize("factory,size", [
    ("mobilenet_v1", 64), ("squeezenet1_0", 64), ("squeezenet1_1", 64),
    ("densenet121", 64), ("shufflenet_v2_x1_0", 64),
    ("resnext101_32x8d", 64)])
def test_new_vision_models_forward(factory, size):
    from paddle_tpu.vision import models as M
    paddle_tpu.seed(0)
    m = getattr(M, factory)(num_classes=7)
    m.eval()
    x = jnp.asarray(rs.randn(1, 3, size, size).astype(np.float32))
    assert m(x).shape == (1, 7)


def test_googlenet_aux_heads_and_training():
    """GoogLeNet trains through its aux heads (reference deep
    supervision) — loss over all three outputs decreases."""
    from paddle_tpu.vision import models as M
    import paddle_tpu.nn.functional as F
    from paddle_tpu.nn.functional_call import functional_call, state
    paddle_tpu.seed(1)
    m = M.googlenet(num_classes=4)
    m.train()
    params, buffers = state(m)
    x = jnp.asarray(rs.randn(2, 3, 96, 96).astype(np.float32))
    y = jnp.asarray(rs.randint(0, 4, (2,)))
    o = opt.Adam(learning_rate=3e-4)
    ostate = o.init(params)
    key = jax.random.PRNGKey(0)

    @jax.jit
    def step(p, os_, b):
        def loss_fn(p):
            (out, a1, a2), nb = functional_call(m, p, b, (x,), rng=key,
                                                train=True)
            return (F.cross_entropy(out, y)
                    + 0.3 * F.cross_entropy(a1, y)
                    + 0.3 * F.cross_entropy(a2, y)), nb
        (l, nb), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
        newp, nos = o.update(g, os_, p)
        return newp, nos, nb, l

    losses = []
    for _ in range(6):
        params, ostate, buffers, loss = step(params, ostate, buffers)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_incubate_segment_ops():
    from paddle_tpu import incubate as inc
    data = np.array([[1., 2.], [3., 4.], [5., 6.], [7., 8.]], np.float32)
    ids = np.array([0, 0, 1, 2])
    np.testing.assert_allclose(np.asarray(inc.segment_sum(data, ids)),
                               [[4., 6.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(np.asarray(inc.segment_mean(data, ids)),
                               [[2., 3.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(np.asarray(inc.segment_max(data, ids)),
                               [[3., 4.], [5., 6.], [7., 8.]])
    np.testing.assert_allclose(np.asarray(inc.segment_min(data, ids)),
                               [[1., 2.], [5., 6.], [7., 8.]])
    # N-D data along axis 0 (review fix: count broadcast)
    d3 = np.ones((4, 2, 3), np.float32)
    m3 = np.asarray(inc.segment_mean(d3, ids))
    assert m3.shape == (3, 2, 3) and np.allclose(m3, 1.0)
    x = rs.randn(2, 4, 4).astype(np.float32)
    out = np.asarray(inc.softmax_mask_fuse_upper_triangle(x))
    assert np.allclose(out.sum(-1), 1.0, atol=1e-6)
    assert (np.triu(out[0], 1) == 0).all()   # causal: no future mass


def test_reduce_lr_on_plateau_callback():
    from paddle_tpu.hapi.callbacks import ReduceLROnPlateau
    import paddle_tpu.optimizer as popt

    class FakeModel:
        pass

    cb = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=2,
                           verbose=0)
    m = FakeModel()
    m._optimizer = popt.SGD(learning_rate=0.1)
    cb.model = m
    for loss in (1.0, 0.9, 0.9, 0.9, 0.9):   # plateaus after step 2
        cb.on_eval_end({"loss": loss})
    assert abs(float(m._optimizer.get_lr()) - 0.05) < 1e-9
    # scales the SCHEDULE base, not the decayed value (review fix):
    # with a decaying scheduler the reduction must not compound decay
    sched = popt.lr.ExponentialDecay(0.1, gamma=0.5)
    m2 = FakeModel()
    m2._optimizer = popt.SGD(learning_rate=sched)
    cb2 = ReduceLROnPlateau(monitor="loss", factor=0.5, patience=1,
                            verbose=0)
    cb2.model = m2
    sched.step()                     # decayed lr now 0.05, base 0.1
    cb2.on_eval_end({"loss": 1.0})
    cb2.on_eval_end({"loss": 1.0})   # plateau -> base 0.1 -> 0.05
    assert abs(float(sched.base_lr) - 0.05) < 1e-9
