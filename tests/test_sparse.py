"""paddle.sparse facade over jax.experimental.sparse (reference:
python/paddle/sparse backed by phi sparse kernels)."""

import numpy as np
import jax.numpy as jnp

import paddle_tpu.sparse as sparse


def test_coo_roundtrip_and_ops():
    idx = np.array([[0, 1, 2], [1, 0, 2]])
    vals = np.array([1.0, 2.0, 3.0], np.float32)
    s = sparse.sparse_coo_tensor(idx, vals, [3, 3])
    assert sparse.is_sparse(s) and sparse.is_sparse_coo(s)
    d = np.zeros((3, 3), np.float32)
    d[idx[0], idx[1]] = vals
    np.testing.assert_allclose(np.asarray(sparse.to_dense(s)), d)
    # add + relu keep sparsity semantics
    out = sparse.add(s, s)
    np.testing.assert_allclose(np.asarray(sparse.to_dense(out)), 2 * d)
    neg = sparse.sparse_coo_tensor(idx, -vals, [3, 3])
    np.testing.assert_allclose(
        np.asarray(sparse.to_dense(sparse.relu(neg))), np.zeros((3, 3)))


def test_csr_and_matmul():
    # csr for [[1,0],[0,2]]
    s = sparse.sparse_csr_tensor([0, 1, 2], [0, 1], [1.0, 2.0], [2, 2])
    assert sparse.is_sparse_csr(s)
    y = jnp.asarray(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
    out = sparse.matmul(s, y)
    np.testing.assert_allclose(np.asarray(out),
                               np.array([[1, 2], [6, 8]], np.float32))


def test_masked_matmul_sddmm():
    rs = np.random.RandomState(0)
    a = jnp.asarray(rs.randn(4, 5).astype(np.float32))
    b = jnp.asarray(rs.randn(5, 4).astype(np.float32))
    idx = np.array([[0, 0], [1, 3], [2, 2]])
    mask = sparse.sparse_coo_tensor(idx.T, np.ones(3, np.float32), [4, 4])
    out = sparse.masked_matmul(a, b, mask)
    dense = np.asarray(a) @ np.asarray(b)
    got = np.asarray(sparse.to_dense(out))
    for r, c in idx:
        np.testing.assert_allclose(got[r, c], dense[r, c], rtol=1e-5)
    assert got[0, 1] == 0.0


def test_to_sparse_and_dense_passthrough():
    x = np.array([[0.0, 1.0], [2.0, 0.0]], np.float32)
    s = sparse.to_sparse_coo(jnp.asarray(x))
    assert sparse.is_sparse(s)
    np.testing.assert_allclose(np.asarray(sparse.to_dense(s)), x)


def test_csr_tag_survives_facade_ops():
    s = sparse.sparse_csr_tensor([0, 1, 2], [0, 1], [1.0, -2.0], [2, 2])
    assert sparse.is_sparse_csr(sparse.relu(s))
    assert sparse.is_sparse_csr(sparse.add(s, s))
    assert sparse.is_sparse_csr(sparse.transpose(s, [1, 0]))


def test_sparse_review_fixes():
    # shape required under jit / for empty
    import pytest as _pytest
    with _pytest.raises(ValueError, match="shape"):
        sparse.sparse_coo_tensor(np.zeros((2, 0), np.int64),
                                 np.zeros((0,), np.float32))
    # O(nnz) transpose keeps values/structure
    s = sparse.sparse_coo_tensor(np.array([[0, 1], [1, 0]]),
                                 np.array([3.0, 4.0], np.float32), [2, 3])
    st = sparse.transpose(s, [1, 0])
    assert st.shape == (3, 2)
    np.testing.assert_allclose(np.asarray(sparse.to_dense(st)),
                               np.asarray(sparse.to_dense(s)).T)


def test_masked_matmul_batched_3d():
    rs = np.random.RandomState(3)
    a = jnp.asarray(rs.randn(2, 3, 4).astype(np.float32))
    b = jnp.asarray(rs.randn(2, 4, 3).astype(np.float32))
    idx = np.array([[0, 1, 2], [1, 0, 0], [1, 2, 1]])
    mask = sparse.sparse_coo_tensor(idx.T, np.ones(3, np.float32),
                                    [2, 3, 3])
    out = np.asarray(sparse.to_dense(sparse.masked_matmul(a, b, mask)))
    dense = np.einsum("bmk,bkn->bmn", np.asarray(a), np.asarray(b))
    for bb, r, c in idx:
        np.testing.assert_allclose(out[bb, r, c], dense[bb, r, c],
                                   rtol=1e-5)
    assert out[0, 0, 0] == 0.0
