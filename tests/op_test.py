"""OpTest harness — the framework's numeric oracle.

Reference: test/legacy_test/op_test.py — OpTest: each op declares
inputs/attrs + a numpy reference; check_output() compares across
places/dtypes; check_grad() does numeric gradient checking against the
registered grad kernel (SURVEY.md §4 "the single most important thing to
replicate").

Ours: check_output = jax impl vs numpy ref per dtype (with per-dtype
tolerance scaling, like the reference's fp16/bf16 tables); check_grad =
central-difference numeric gradient vs jax.grad on a scalarized output.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.ops.registry import OpDef

_DTYPE_TOL = {
    "float32": (1.0, 1.0),
    "float64": (1.0, 1.0),
    "float16": (300.0, 300.0),
    "bfloat16": (2000.0, 2000.0),
}


def _cast_sample(args, dtype):
    out = []
    for a in args:
        if isinstance(a, np.ndarray) and a.dtype in (np.float32, np.float64):
            out.append(a.astype(dtype))
        else:
            out.append(a)
    return tuple(out)


def check_output(op: OpDef):
    args, kwargs = op.sample()
    for dtype in op.dtypes:
        f_r, f_a = _DTYPE_TOL.get(dtype, (1.0, 1.0))
        cargs = _cast_sample(args, np.float32 if dtype in ("float16", "bfloat16")
                             else dtype)
        jargs = tuple(jnp.asarray(a).astype(dtype) if isinstance(a, np.ndarray)
                      and np.issubdtype(a.dtype, np.floating) else
                      (jnp.asarray(a) if isinstance(a, np.ndarray) else a)
                      for a in cargs)
        out = op.fn(*jargs, **kwargs)
        if op.ref is None:
            # smoke: finite & shaped
            for leaf in jax.tree.leaves(out):
                assert np.all(np.isfinite(np.asarray(leaf, dtype=np.float32))), \
                    f"{op.name}: non-finite output"
            continue
        ref = op.ref(*cargs, **kwargs)
        out_np = np.asarray(out).astype(np.float32) if hasattr(out, "dtype") else out
        ref_np = np.asarray(ref, dtype=out_np.dtype if hasattr(out_np, "dtype") else None)
        np.testing.assert_allclose(
            out_np, ref_np.astype(np.float32) if hasattr(ref_np, "dtype") and
            np.issubdtype(ref_np.dtype, np.floating) else ref_np,
            rtol=op.rtol * f_r, atol=op.atol * f_a,
            err_msg=f"op {op.name} dtype {dtype}")


def check_grad(op: OpDef, eps: float = 1e-3):
    """Numeric central-difference vs autodiff, on sum(out * cotangent)."""
    if not op.grad_args:
        return
    args, kwargs = op.sample()
    jargs = [jnp.asarray(a) if isinstance(a, np.ndarray) else a for a in args]
    out0 = op.fn(*jargs, **kwargs)
    cot = np.random.RandomState(7).uniform(0.5, 1.5,
                                           np.shape(out0)).astype(np.float32)

    def scalar_fn(*gargs):
        full = list(jargs)
        for slot, val in zip(op.grad_args, gargs):
            full[slot] = val
        out = op.fn(*full, **kwargs)
        return jnp.sum(out * jnp.asarray(cot))

    grad_inputs = tuple(jargs[i] for i in op.grad_args)
    auto = jax.jit(jax.grad(scalar_fn, argnums=tuple(range(len(grad_inputs)))))(
        *grad_inputs)

    for slot_idx, (slot, g_auto) in enumerate(zip(op.grad_args, auto)):
        base = np.asarray(args[slot], dtype=np.float32)
        n = base.size
        # vectorized central differences: two vmapped evals over N perturbed
        # copies each (element-wise host loops like the reference OpTest are
        # too slow on this CPU backend)
        eye = (np.eye(n, dtype=np.float32) * eps).reshape((n,) + base.shape)
        plus = base[None] + eye
        minus = base[None] - eye

        def eval_slot(x):
            vals = list(grad_inputs)
            vals[slot_idx] = x
            return scalar_fn(*vals)

        batched = jax.jit(jax.vmap(eval_slot))
        f_plus = batched(jnp.asarray(plus))
        f_minus = batched(jnp.asarray(minus))
        g_num = (np.asarray(f_plus, np.float64) -
                 np.asarray(f_minus, np.float64)).reshape(base.shape) / (2 * eps)
        np.testing.assert_allclose(
            np.asarray(g_auto, dtype=np.float64), g_num,
            rtol=op.grad_rtol, atol=op.grad_atol,
            err_msg=f"op {op.name} grad arg {slot}")
