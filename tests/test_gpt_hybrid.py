"""GPT hybrid-parallel tests: the reference's PP/TP oracle — pipelined
hybrid loss == serial loss with identical weights (model:
test/collective/fleet/test_parallel_dygraph_pipeline_parallel.py)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from conftest import requires_modern_jax

import paddle_tpu
import paddle_tpu.distributed as dist
import paddle_tpu.optimizer as opt
from paddle_tpu.models import gpt_tiny, GPTForCausalLM, GPTHybridTrainer
from paddle_tpu.nn.functional_call import functional_call, state


def _mk_trainer(hybrid, microbatches=2, seed=11):
    s = dist.DistributedStrategy()
    s.hybrid_configs = hybrid
    dist.fleet.init(is_collective=True, strategy=s)
    hcg = dist.get_hybrid_communicate_group()
    paddle_tpu.seed(seed)
    cfg = gpt_tiny(remat=False)
    tr = GPTHybridTrainer(cfg, hcg, opt.SGD(learning_rate=0.1),
                          microbatches=microbatches)
    return tr


def teardown_function(_fn):
    dist.topology.set_hybrid_communicate_group(None)


def test_remat_actually_applied_and_policy_parity():
    """cfg.remat must materialize as checkpoint regions in the lowered
    grad program (review finding: GPTForCausalLM silently ignored it and
    the bench recorded remat metadata that never took effect), and every
    remat mode computes identical losses."""
    import dataclasses
    import paddle_tpu.nn.functional as F

    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 128, (2, 16)))
    labels = jnp.asarray(rng.integers(0, 128, (2, 16)))

    def build(remat):
        paddle_tpu.seed(5)
        cfg = dataclasses.replace(gpt_tiny(remat=remat), vocab_size=128)
        m = GPTForCausalLM(cfg)
        params, buffers = state(m)

        def loss_fn(p):
            out, _ = functional_call(m, p, buffers, (ids,))
            return jnp.mean(F.cross_entropy(
                out.reshape(-1, 128), labels.reshape(-1)))

        return loss_fn, params

    def grad_jaxpr_and_loss(remat):
        # fresh model per trace: make_jaxpr leaves traced buffers behind
        # in the Layer, which must not leak into the value evaluation
        loss_fn, params = build(remat)
        jaxpr = str(jax.make_jaxpr(jax.grad(loss_fn))(params))
        loss_fn2, params2 = build(remat)
        return jaxpr, float(loss_fn2(params2))

    jp_on, l_on = grad_jaxpr_and_loss(True)
    jp_pol, l_pol = grad_jaxpr_and_loss("dots_saveable")
    jp_off, l_off = grad_jaxpr_and_loss(False)
    assert "remat" in jp_on
    assert "remat" in jp_pol
    assert "remat" not in jp_off
    np.testing.assert_allclose(l_on, l_off, rtol=1e-6)
    np.testing.assert_allclose(l_pol, l_off, rtol=1e-6)

    # unknown policy names fail loudly with the known list — including
    # jax.checkpoint_policies FACTORY attrs, which are not policies and
    # would silently save everything (review finding)
    from paddle_tpu.distributed.recompute import remat_wrap
    for bad in ("definitely_not_a_policy", "save_only_these_names"):
        with pytest.raises(ValueError, match="known:"):
            remat_wrap(lambda x: x, bad)(jnp.ones(()))


@requires_modern_jax
def test_pipeline_loss_matches_serial():
    """Same init (fixed seed) run dp1/mp1/pp1 vs dp2/mp2/pp2: losses equal."""
    tr1 = _mk_trainer({"dp_degree": 1, "mp_degree": 1, "pp_degree": 1},
                      microbatches=2)
    st1 = tr1.init_state()
    x, y = tr1.make_batch(batch=4, seq=16, seed=5)
    st1, loss1 = tr1.train_step(st1, x, y)
    st1, loss1b = tr1.train_step(st1, x, y)
    dist.topology.set_hybrid_communicate_group(None)

    tr2 = _mk_trainer({"dp_degree": 2, "mp_degree": 2, "pp_degree": 2},
                      microbatches=2)
    st2 = tr2.init_state()
    x2, y2 = tr2.make_batch(batch=4, seq=16, seed=5)
    st2, loss2 = tr2.train_step(st2, x2, y2)
    st2, loss2b = tr2.train_step(st2, x2, y2)

    np.testing.assert_allclose(float(loss1), float(loss2), rtol=2e-4)
    # after one update the trajectories still match -> grads matched too
    np.testing.assert_allclose(float(loss1b), float(loss2b), rtol=2e-3)


def test_pipeline_microbatch_counts():
    tr = _mk_trainer({"dp_degree": 1, "mp_degree": 1, "pp_degree": 2},
                     microbatches=4)
    st = tr.init_state()
    x, y = tr.make_batch(batch=8, seq=16)
    st, loss = tr.train_step(st, x, y)
    assert np.isfinite(float(loss))


def test_gpt_decode_cache_matches_full():
    """Incremental decode == full forward (the fused_multi_transformer
    correctness contract)."""
    paddle_tpu.seed(0)
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    model.eval()
    params, buffers = state(model)
    ids = jnp.asarray(np.random.RandomState(0).randint(0, cfg.vocab_size,
                                                       (2, 8)))

    full_logits, _ = functional_call(model, params, buffers, (ids,),
                                     train=False)

    # incremental decode through bind_state
    from paddle_tpu.nn.functional_call import bind_state
    with bind_state(model, params, buffers):
        caches = model.init_cache(batch=2, max_len=16)
        step_logits = []
        for t in range(8):
            lg, caches = model.decode_step(ids[:, t:t + 1], caches, t)
            step_logits.append(lg[:, 0])
    stepped = jnp.stack(step_logits, axis=1)
    # measured max abs diff ~3e-7 on the CPU highest-precision path; the
    # only "large" relative errors sit at near-zero logits, which atol
    # absorbs (round-2 review asked for the old rtol=2e-2 to be justified
    # or tightened — tightened)
    np.testing.assert_allclose(np.asarray(stepped), np.asarray(full_logits),
                               rtol=1e-3, atol=1e-5)


def test_gpt_tie_embeddings_single_table():
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    params, _ = state(model)
    assert not any("lm_head" in k for k in params)
    n = model.cfg.num_params()
    actual = sum(int(np.prod(p.shape)) for p in params.values())
    assert abs(n - actual) / actual < 0.02


def test_gpt_chunked_prefill_parity():
    # decode_step with s>1 chunks must stay causal within the chunk
    paddle_tpu.seed(11)
    cfg = gpt_tiny()
    model = GPTForCausalLM(cfg)
    model.eval()
    ids = jnp.asarray(np.random.RandomState(4).randint(0, 256, (2, 12)))
    full = model(ids)
    caches = model.init_cache(2, 32)
    outs = []
    for lo, hi in [(0, 5), (5, 8), (8, 12)]:
        lg, caches = model.decode_step(ids[:, lo:hi], caches, lo)
        outs.append(lg)
    step = jnp.concatenate(outs, axis=1)
    np.testing.assert_allclose(np.asarray(step), np.asarray(full),
                               rtol=2e-4, atol=2e-4)


def _mk_trainer_zero(hybrid, zero, microbatches=2, seed=31):
    s = dist.DistributedStrategy()
    s.hybrid_configs = hybrid
    dist.fleet.init(is_collective=True, strategy=s)
    hcg = dist.get_hybrid_communicate_group()
    paddle_tpu.seed(seed)
    cfg = gpt_tiny(remat=False)
    tr = GPTHybridTrainer(cfg, hcg, opt.SGD(learning_rate=0.1),
                          microbatches=microbatches, zero_stage=zero)
    return tr


@pytest.mark.parametrize("zero", [2, 3])
def test_zero_stage_parity_vs_serial(zero):
    """ZeRO-2/3 over sharding_degree=4 trains identically to serial
    (reference oracle: sharding stage2/3 tests vs DP —
    test/collective/fleet hybrid_parallel_sharding_model)."""
    tr1 = _mk_trainer_zero({"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                            "sharding_degree": 1}, zero=1)
    st1 = tr1.init_state()
    x, y = tr1.make_batch(batch=8, seq=16, seed=7)
    st1, l1a = tr1.train_step(st1, x, y)
    st1, l1b = tr1.train_step(st1, x, y)
    dist.topology.set_hybrid_communicate_group(None)

    tr2 = _mk_trainer_zero({"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                            "sharding_degree": 4}, zero=zero)
    st2 = tr2.init_state()
    x2, y2 = tr2.make_batch(batch=8, seq=16, seed=7)
    st2, l2a = tr2.train_step(st2, x2, y2)
    st2, l2b = tr2.train_step(st2, x2, y2)

    np.testing.assert_allclose(float(l1a), float(l2a), rtol=2e-4)
    np.testing.assert_allclose(float(l1b), float(l2b), rtol=2e-3)


def test_zero3_param_bytes_shrink_per_device():
    """Stage 3 stores parameters sharded: a shardable leaf's per-device
    bytes must be total/degree (the ZeRO-3 memory property)."""
    deg = 4
    tr = _mk_trainer_zero({"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                           "sharding_degree": deg}, zero=3)
    pnb, pblk, _, _ = tr.init_state()
    # the stacked block qkv weight is large and shardable
    leaf = pblk["qkv.weight"]
    shard_elems = leaf.addressable_shards[0].data.size
    assert any("sharding" in (ax if isinstance(ax, tuple) else (ax,))
               for ax in tr.specs_blocks["qkv.weight"] if ax is not None)
    assert shard_elems * deg == leaf.size, (shard_elems, leaf.size)
    # and a stage-1 trainer keeps params whole per device
    dist.topology.set_hybrid_communicate_group(None)
    tr1 = _mk_trainer_zero({"dp_degree": 1, "mp_degree": 1, "pp_degree": 1,
                            "sharding_degree": deg}, zero=1)
    pnb1, pblk1, _, _ = tr1.init_state()
    assert pblk1["qkv.weight"].addressable_shards[0].data.size == \
        pblk1["qkv.weight"].size


def test_vpp_trainer_matches_serial():
    """GPT hybrid trainer with the interleaved (VPP) schedule: pp2 x vpp2
    over 4 layers == serial loss trajectory."""
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1}
    dist.fleet.init(is_collective=True, strategy=s)
    paddle_tpu.seed(41)
    cfg = gpt_tiny(remat=False)
    cfg.num_layers = 4
    tr1 = GPTHybridTrainer(cfg, dist.get_hybrid_communicate_group(),
                           opt.SGD(learning_rate=0.1), microbatches=2)
    st1 = tr1.init_state()
    x, y = tr1.make_batch(batch=4, seq=16, seed=9)
    st1, l1a = tr1.train_step(st1, x, y)
    st1, l1b = tr1.train_step(st1, x, y)
    dist.topology.set_hybrid_communicate_group(None)

    s2 = dist.DistributedStrategy()
    s2.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 2}
    dist.fleet.init(is_collective=True, strategy=s2)
    paddle_tpu.seed(41)
    cfg2 = gpt_tiny(remat=False)
    cfg2.num_layers = 4
    tr2 = GPTHybridTrainer(cfg2, dist.get_hybrid_communicate_group(),
                           opt.SGD(learning_rate=0.1), microbatches=2,
                           vpp=2)
    st2 = tr2.init_state()
    x2, y2 = tr2.make_batch(batch=4, seq=16, seed=9)
    st2, l2a = tr2.train_step(st2, x2, y2)
    st2, l2b = tr2.train_step(st2, x2, y2)

    np.testing.assert_allclose(float(l1a), float(l2a), rtol=2e-4)
    np.testing.assert_allclose(float(l1b), float(l2b), rtol=2e-3)


@requires_modern_jax
def test_vpp_trainer_with_mp_matches_serial():
    """VPP composed with tensor parallel: pp2 x vpp2 x mp2 == serial
    (settles that partial-manual shard_map keeps mp shardings intact on
    the interleaved path)."""
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 1}
    dist.fleet.init(is_collective=True, strategy=s)
    paddle_tpu.seed(43)
    cfg = gpt_tiny(remat=False)
    cfg.num_layers = 4
    tr1 = GPTHybridTrainer(cfg, dist.get_hybrid_communicate_group(),
                           opt.SGD(learning_rate=0.1), microbatches=2)
    st1 = tr1.init_state()
    x, y = tr1.make_batch(batch=4, seq=16, seed=13)
    st1, l1a = tr1.train_step(st1, x, y)
    st1, l1b = tr1.train_step(st1, x, y)
    dist.topology.set_hybrid_communicate_group(None)

    s2 = dist.DistributedStrategy()
    s2.hybrid_configs = {"dp_degree": 1, "mp_degree": 2, "pp_degree": 2}
    dist.fleet.init(is_collective=True, strategy=s2)
    paddle_tpu.seed(43)
    cfg2 = gpt_tiny(remat=False)
    cfg2.num_layers = 4
    tr2 = GPTHybridTrainer(cfg2, dist.get_hybrid_communicate_group(),
                           opt.SGD(learning_rate=0.1), microbatches=2,
                           vpp=2)
    st2 = tr2.init_state()
    # mp-sharded stacked block leaves must actually BE mp-sharded on device
    qkv = st2[1]["qkv.weight"]
    assert any(ax == "mp" for ax in jax.tree_util.tree_leaves(
        [list(tr2.specs_blocks["qkv.weight"])]) if ax is not None) or \
        "mp" in str(tr2.specs_blocks["qkv.weight"])
    x2, y2 = tr2.make_batch(batch=4, seq=16, seed=13)
    st2, l2a = tr2.train_step(st2, x2, y2)
    st2, l2b = tr2.train_step(st2, x2, y2)

    np.testing.assert_allclose(float(l1a), float(l2a), rtol=2e-4)
    np.testing.assert_allclose(float(l1b), float(l2b), rtol=2e-3)


@requires_modern_jax
def test_vpp_with_zero3_trains_and_shards():
    """VPP interleaving composed with ZeRO-3 param sharding: trains, and
    the two-level stacked block leaves are actually sharded."""
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 1, "mp_degree": 1, "pp_degree": 2,
                        "sharding_degree": 2}
    dist.fleet.init(is_collective=True, strategy=s)
    paddle_tpu.seed(51)
    cfg = gpt_tiny(remat=False)
    cfg.num_layers = 4
    tr = GPTHybridTrainer(cfg, dist.get_hybrid_communicate_group(),
                          opt.AdamW(learning_rate=1e-3), microbatches=2,
                          zero_stage=3, vpp=2)
    st = tr.init_state()
    pblk = st[1]
    leaf = pblk["qkv.weight"]          # [S*V, K, h, 3h]
    assert leaf.ndim == 4
    spec = tr.specs_blocks["qkv.weight"]
    assert "sharding" in str(spec)     # zero-3 sharded stacked leaf
    assert leaf.addressable_shards[0].data.size < leaf.size
    x, y = tr.make_batch(batch=4, seq=16, seed=3)
    l0 = None
    for _ in range(4):
        st, loss = tr.train_step(st, x, y)
        if l0 is None:
            l0 = float(loss)
    assert float(loss) < l0


@requires_modern_jax
def test_vocab_table_not_replicated_across_pp():
    """Stage assignment of embedding + tied head, SPMD-style (reference
    SharedLayerDesc, SURVEY §2.3 PP row): with pp>1 the wte table's rows are
    sharded over the pp axis, so per-device bytes drop by the pp degree
    instead of every pipeline stage holding a full replica (round-2 VERDICT
    item 3)."""
    tr = _mk_trainer({"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
                      "sharding_degree": 2}, microbatches=2)
    pnb, _, _, _ = tr.init_state()
    wte = pnb["gpt.wte.weight"]
    total = wte.size * wte.dtype.itemsize
    shard = wte.addressable_shards[0].data
    per_dev = shard.size * shard.dtype.itemsize
    # vocab rows split over mp(2) x pp(2) -> each device holds 1/4
    assert per_dev * 4 == total, (per_dev, total)
    # spec carries pp on the row dim
    spec0 = wte.sharding.spec[0]
    flat = spec0 if isinstance(spec0, tuple) else (spec0,)
    assert "pp" in flat and "mp" in flat
    # and training still works on this layout (parity vs serial is covered
    # by test_pipeline_loss_matches_serial, which runs pp2 with the same
    # sharded-table path)
    x, y = tr.make_batch(batch=4, seq=16)
    _, loss = tr.train_step(tr.init_state(), x, y)
    assert np.isfinite(float(loss))


def test_gpt_matches_transformers_gpt2_weight_mapped():
    """Architectural exactness vs a weight-mapped transformers.GPT2Model
    (config-only, no network): pre-LN blocks, fused c_attn == our fused
    qkv ([h, 3h], Conv1D stores [in, out] so no transpose), tanh-gelu."""
    import torch
    from transformers import GPT2Config as HFConfig, GPT2Model as HFModel
    from paddle_tpu.models import GPTConfig, GPTForCausalLM

    hf_cfg = HFConfig(vocab_size=256, n_positions=64, n_embd=64,
                      n_layer=2, n_head=4, resid_pdrop=0.0,
                      embd_pdrop=0.0, attn_pdrop=0.0,
                      activation_function="gelu_new")
    torch.manual_seed(0)
    hf = HFModel(hf_cfg).eval()

    paddle_tpu.seed(0)
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                    num_heads=4, max_seq_len=64, dropout=0.0, remat=False)
    mine = GPTForCausalLM(cfg)
    mine.eval()

    # map straight into the BACKBONE's parameter dict (same shape as the
    # llama parity test)
    sd = {k: v.detach().numpy() for k, v in hf.state_dict().items()}
    mapped, _ = state(mine.gpt)
    mapped = dict(mapped)
    mapped["wte.weight"] = jnp.asarray(sd["wte.weight"])
    mapped["wpe.weight"] = jnp.asarray(sd["wpe.weight"])
    mapped["ln_f.weight"] = jnp.asarray(sd["ln_f.weight"])
    mapped["ln_f.bias"] = jnp.asarray(sd["ln_f.bias"])
    for i in range(2):
        hp, mp = f"h.{i}", f"h.{i}"
        for ln in ("ln_1", "ln_2"):
            mapped[f"{mp}.{ln}.weight"] = jnp.asarray(
                sd[f"{hp}.{ln}.weight"])
            mapped[f"{mp}.{ln}.bias"] = jnp.asarray(sd[f"{hp}.{ln}.bias"])
        # GPT-2 Conv1D weights are [in, out] — our Linear layout exactly
        mapped[f"{mp}.qkv.weight"] = jnp.asarray(
            sd[f"{hp}.attn.c_attn.weight"])
        mapped[f"{mp}.qkv.bias"] = jnp.asarray(sd[f"{hp}.attn.c_attn.bias"])
        mapped[f"{mp}.out_proj.weight"] = jnp.asarray(
            sd[f"{hp}.attn.c_proj.weight"])
        mapped[f"{mp}.out_proj.bias"] = jnp.asarray(
            sd[f"{hp}.attn.c_proj.bias"])
        mapped[f"{mp}.fc_in.weight"] = jnp.asarray(
            sd[f"{hp}.mlp.c_fc.weight"])
        mapped[f"{mp}.fc_in.bias"] = jnp.asarray(sd[f"{hp}.mlp.c_fc.bias"])
        mapped[f"{mp}.fc_out.weight"] = jnp.asarray(
            sd[f"{hp}.mlp.c_proj.weight"])
        mapped[f"{mp}.fc_out.bias"] = jnp.asarray(
            sd[f"{hp}.mlp.c_proj.bias"])

    ids = np.random.RandomState(5).randint(0, 256, (2, 12))
    with torch.no_grad():
        ref = hf(input_ids=torch.tensor(ids)).last_hidden_state.numpy()
    hidden, _ = functional_call(mine.gpt, mapped, {},
                                (jnp.asarray(ids),), train=False)
    np.testing.assert_allclose(np.asarray(hidden), ref, rtol=2e-4,
                               atol=2e-4)


def test_bf16_hybrid_state_layout():
    """cfg.dtype="bfloat16" casts the model BEFORE the layout snapshot:
    sharded params come out bf16 with f32 multi-precision masters (the
    north-star dtype layout — the full bf16 STEP only compiles sanely on
    TPU; XLA:CPU's bf16 emulation of this program is pathological, so the
    step itself is exercised by the on-chip bench, not here)."""
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 2, "pp_degree": 2}
    dist.fleet.init(is_collective=True, strategy=s)
    hcg = dist.get_hybrid_communicate_group()
    paddle_tpu.seed(21)
    cfg = gpt_tiny(remat=True)
    cfg.dtype = "bfloat16"
    tr = GPTHybridTrainer(
        cfg, hcg, opt.AdamW(learning_rate=3e-3, multi_precision=True),
        microbatches=2, zero_stage=1)
    pnb, pblk, onb, oblk = tr.init_state()
    assert pblk["qkv.weight"].dtype == jnp.bfloat16
    assert pnb["gpt.wte.weight"].dtype == jnp.bfloat16
    # EVERY floating param gets an f32 master (a None would mean the
    # cast missed it), on both the nonblock and stacked-block sides
    for tree in (onb["master"], oblk["master"]):
        assert tree and all(
            v is not None and v.dtype == jnp.float32
            for v in tree.values())
    # AdamW slots are f32 regardless of param dtype
    for per_param in onb["slots"].values():
        for v in per_param.values():
            assert v.dtype == jnp.float32


@requires_modern_jax
def test_bf16_hybrid_pipeline_compiles_and_learns():
    """bf16 + pp>1 regression (round 5): shardy's HLO round-trip emits
    copy-rooted BF16 psum combiners that CHECK-crash XLA ("Invalid
    binary instruction opcode copy") — hit by the pipeline shard_map's
    replicated-queue cotangent psum and by bf16 scatter-add embedding
    grads.  Guards the two fixes: the f32 pipeline queue boundary
    (pipelining._f32_queue) and the f32 scatter-accumulate table lookup
    (mp_layers._take_rows_f32grad).  Before the fixes this exact config
    aborted the process, so this test doubles as a compile-success gate
    for the 6.7B AOT north-star mesh shape (dp x sharding x pp x mp)."""
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 1, "mp_degree": 2, "pp_degree": 2,
                        "sharding_degree": 2}
    dist.fleet.init(is_collective=True, strategy=s)
    hcg = dist.get_hybrid_communicate_group()
    paddle_tpu.seed(5)
    from paddle_tpu.models.gpt import GPTConfig
    cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=4,
                    num_heads=4, max_seq_len=64, dtype="bfloat16",
                    sp=True, remat=True)
    tr = GPTHybridTrainer(cfg, hcg,
                          opt.AdamW(learning_rate=1e-2,
                                    multi_precision=True),
                          microbatches=4, zero_stage=1)
    st = tr.init_state()
    x, y = tr.make_batch(batch=16, seq=32, seed=3)
    st, l1 = tr.train_step(st, x, y)
    for _ in range(4):
        st, l2 = tr.train_step(st, x, y)
    l1, l2 = float(l1), float(l2)
    assert np.isfinite(l1) and np.isfinite(l2)
    assert l1 < 2.0 * np.log(cfg.vocab_size)      # vocab-scale init CE
    assert l2 < l1                                # memorizes the batch
