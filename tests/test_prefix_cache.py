"""Radix prefix cache + chunked prefill (serving/prefix_cache.py).

The load-bearing contracts:
  * PARITY — with the cache enabled, greedy and seeded-sampled engine
    outputs are token-for-token identical to the cache-off engine (and
    to ``model.generate``) for full hits, partial hits, misses, and
    re-admission after LRU eviction.  The cache moves KV bytes, never
    changes them;
  * COMPILE BOUNDING — chunked prefill keeps the program count
    O(log2(max_seq / min_bucket)) + ONE decode program, plus ONE block
    gather and ONE block scatter, regardless of prompt lengths or hit
    patterns;
  * LIFECYCLE — refcounts pin matched paths while their requests run,
    eviction only ever takes LRU unpinned leaves, and the block pool's
    accounting survives slot over-subscription stress;
  * SCHEDULING — the head-of-line skip admits a fitting later request
    past an oversized head, bounded by the skip window and the
    no-starvation counter.
"""

import math

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import (GPTForCausalLM, LlamaConfig,
                               LlamaForCausalLM, gpt_tiny)
from paddle_tpu.serving import (BlockPool, PrefixCache, SamplingParams,
                                Scheduler, ServingEngine)
from paddle_tpu.serving.scheduler import Request


@pytest.fixture(scope="module")
def gpt():
    with jax.default_prng_impl("rbg"):
        return GPTForCausalLM(gpt_tiny())


@pytest.fixture(scope="module")
def eng(gpt):
    """Shared cache-on engine: block_len 8 so short test prompts hit."""
    return ServingEngine(gpt, num_slots=3, min_bucket=8, block_len=8)


def _prompts(seed, lengths, vocab=256):
    rs = np.random.RandomState(seed)
    return [rs.randint(0, vocab, (L,)) for L in lengths]


def _shared_prefix_prompts(seed, pref_len, suffix_lens, vocab=256):
    rs = np.random.RandomState(seed)
    pref = rs.randint(0, vocab, (pref_len,))
    return [np.concatenate([pref, rs.randint(0, vocab, (s,))])
            for s in suffix_lens]


def _want_tokens(model, prompt, n=5, **kw):
    seq = model.generate(jnp.asarray(prompt)[None], max_new_tokens=n, **kw)
    return np.asarray(seq)[0, len(prompt):]


# ---------------------------------------------------------------- parity

def test_full_hit_parity_and_accounting(gpt, eng):
    """The same prompt twice: the repeat matches every full block except
    the one holding the last token (at least one token must prefill) and
    still reproduces generate() exactly."""
    p = _prompts(0, (41,))[0]
    o1 = eng.serve_batch([p], max_new_tokens=5, max_steps=200)[0]
    o2 = eng.serve_batch([p], max_new_tokens=5, max_steps=200)[0]
    want = _want_tokens(gpt, p)
    np.testing.assert_array_equal(np.asarray(o1.tokens), want)
    np.testing.assert_array_equal(np.asarray(o2.tokens), want)
    assert o1.prefix_hit_tokens == 0
    assert o2.prefix_hit_tokens == (41 - 1) // 8 * 8 == 40


def test_partial_hit_parity(gpt, eng):
    """Prompts sharing a 24-token prefix with divergent tails: each
    later request hits exactly the shared blocks and its output still
    matches its own solo generate()."""
    prompts = _shared_prefix_prompts(1, 24, (7, 12, 3))
    outs = eng.serve_batch(prompts, max_new_tokens=5, max_steps=300)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(np.asarray(o.tokens),
                                      _want_tokens(gpt, p))
    # all three were admitted together (3 slots): the first inserts, the
    # later two may match depending on admission order — re-serving the
    # same prompts must now hit the shared prefix on every request
    outs2 = eng.serve_batch(prompts, max_new_tokens=5, max_steps=300)
    for p, o in zip(prompts, outs2):
        np.testing.assert_array_equal(np.asarray(o.tokens),
                                      _want_tokens(gpt, p))
        assert o.prefix_hit_tokens >= 24 // 8 * 8


def test_sampled_parity_with_prefix_hit(gpt, eng):
    """Seeded sampling through the cache-hit path reproduces
    generate(seed=...) exactly — the copied KV is bit-identical, so the
    sampled trajectory is too."""
    p = _shared_prefix_prompts(2, 32, (9,))[0]
    kw = dict(do_sample=True, temperature=1.6, top_k=7, top_p=0.9, seed=13)
    eng.serve_batch([p], max_new_tokens=4, max_steps=200)   # seed the tree
    rid = eng.submit(p, max_new_tokens=5, sampling=SamplingParams(**kw))
    eng.run_until_complete(200)
    out = eng.result(rid)
    assert out.prefix_hit_tokens > 0
    np.testing.assert_array_equal(np.asarray(out.tokens),
                                  _want_tokens(gpt, p, 5, **kw))


def test_cache_on_off_identical_outputs(gpt):
    """The same mixed workload through cache-on and cache-off engines:
    byte-identical token streams."""
    prompts = _shared_prefix_prompts(3, 16, (2, 9, 20)) + \
        _prompts(4, (5, 30))
    on = ServingEngine(gpt, num_slots=2, min_bucket=8, block_len=8)
    off = ServingEngine(gpt, num_slots=2, min_bucket=8,
                        enable_prefix_cache=False)
    a = on.serve_batch(prompts, max_new_tokens=4, max_steps=400)
    b = off.serve_batch(prompts, max_new_tokens=4, max_steps=400)
    for oa, ob in zip(a, b):
        assert oa.tokens == ob.tokens
    # and a second pass (now with hits) still agrees
    a2 = on.serve_batch(prompts, max_new_tokens=4, max_steps=400)
    for oa, ob in zip(a2, b):
        assert oa.tokens == ob.tokens
    assert on.metrics_dict()["prefix_hit_tokens"] > 0


def test_post_eviction_readmission_parity(gpt):
    """A pool too small for two prompts' blocks: inserting the second
    evicts the first's LRU leaves; re-admitting the first recomputes and
    still matches generate()."""
    engine = ServingEngine(gpt, num_slots=1, min_bucket=8, block_len=8,
                           prefix_blocks=4)               # 32 tokens max
    pa, pb = _prompts(5, (33, 40))
    want_a, want_b = _want_tokens(gpt, pa), _want_tokens(gpt, pb)
    o = engine.serve_batch([pa], max_new_tokens=5, max_steps=200)[0]
    np.testing.assert_array_equal(np.asarray(o.tokens), want_a)
    o = engine.serve_batch([pb], max_new_tokens=5, max_steps=200)[0]
    np.testing.assert_array_equal(np.asarray(o.tokens), want_b)
    stats = engine.metrics_dict()["prefix_cache"]
    assert stats["prefix_evictions"] > 0                  # pa's blocks
    o = engine.serve_batch([pa], max_new_tokens=5, max_steps=200)[0]
    np.testing.assert_array_equal(np.asarray(o.tokens), want_a)


def test_llama_gqa_prefix_parity():
    """The block slab uses kv_heads (GQA: fewer KV heads than query
    heads) — gather/scatter must round-trip that layout exactly."""
    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    prompts = _shared_prefix_prompts(6, 16, (3, 6), vocab=128)
    engine = ServingEngine(model, num_slots=2, min_bucket=8, block_len=8)
    engine.serve_batch(prompts, max_new_tokens=4, max_steps=200)
    outs = engine.serve_batch(prompts, max_new_tokens=4, max_steps=200)
    for p, o in zip(prompts, outs):
        assert o.prefix_hit_tokens == 16
        np.testing.assert_array_equal(np.asarray(o.tokens),
                                      _want_tokens(model, p, 4))


# ------------------------------------------------------- chunked prefill

def test_chunked_prefill_parity(gpt):
    """A long prompt split into fixed chunks decodes identically to the
    whole-suffix prefill."""
    p = _prompts(7, (100,))[0]
    engine = ServingEngine(gpt, num_slots=2, min_bucket=8,
                           prefill_chunk=16, block_len=8)
    o = engine.serve_batch([p], max_new_tokens=5, max_steps=500)[0]
    np.testing.assert_array_equal(np.asarray(o.tokens), _want_tokens(gpt, p))
    m = engine.metrics_dict()
    assert m["prefill_chunks"] == math.ceil(100 / 16)


def test_chunked_prefill_interleaves_with_decode(gpt):
    """THE stall bound: while a long prompt chunks through prefill, an
    in-flight stream keeps emitting one token per engine step — decode
    never waits for the whole admission."""
    engine = ServingEngine(gpt, num_slots=2, min_bucket=8,
                           prefill_chunk=16, block_len=8)
    short = _prompts(8, (5,))[0]
    rid_s = engine.submit(short, max_new_tokens=30)
    engine.step()                                  # short is decoding
    base = len(engine.core._slots[next(iter(engine.core._slots))].req.tokens)
    long_p = _prompts(9, (90,))[0]
    rid_l = engine.submit(long_p, max_new_tokens=2)
    n_chunks = math.ceil(90 / 16)
    for i in range(n_chunks):
        engine.step()
        # the running stream advanced EVERY step of the long prefill
        assert len(engine._requests[rid_s].tokens) == base + i + 1
    assert len(engine._requests[rid_l].tokens) >= 1   # first token landed
    engine.run_until_complete(200)
    np.testing.assert_array_equal(
        np.asarray(engine.result(rid_s).tokens),
        _want_tokens(gpt, short, 30))
    np.testing.assert_array_equal(
        np.asarray(engine.result(rid_l).tokens),
        _want_tokens(gpt, long_p, 2))


def test_chunk_plan_covers_suffix_exactly():
    s = Scheduler(num_slots=2, max_seq=128, min_bucket=8)
    # legacy: one pow2-bucketed chunk
    assert s.chunk_plan(0, 50, None) == [(0, 64, 50)]
    # chunked: fixed pieces + bucketed tail
    plan = s.chunk_plan(0, 50, 16)
    assert plan == [(0, 16, 16), (16, 16, 16), (32, 16, 16), (48, 8, 2)]
    assert sum(v for _, _, v in plan) == 50
    # suffix after a 40-token cache hit
    plan = s.chunk_plan(40, 50, 16)
    assert plan == [(40, 16, 10)]
    # widths never overrun the cache row
    plan = s.chunk_plan(120, 125, None)
    assert plan == [(120, 8, 5)]


def test_compile_count_bounded_with_cache_and_chunks(gpt):
    """The fixed-shape contract, extended: mixed lengths + cache hits +
    chunked prefill lower at most {chunk width} + O(log2 buckets)
    prefill programs, ONE decode program, ONE block gather and ONE block
    scatter — hit patterns and prompt diversity never leak into the
    compile cache."""
    engine = ServingEngine(gpt, num_slots=3, min_bucket=8,
                           prefill_chunk=16, block_len=16)
    lengths = (3, 9, 17, 33, 50)
    prompts = _prompts(10, lengths)
    rids = [engine.submit(p, max_new_tokens=3) for p in prompts]
    engine.run_until_complete(500)
    # re-serve the longest prompt now that its blocks are cached: the
    # hit path (block gather) must not add programs either
    rids.append(engine.submit(prompts[-1].copy(), max_new_tokens=3))
    engine.run_until_complete(100)
    out = engine.result(rids[-1])
    assert out.prefix_hit_tokens == 48              # 3 of 3 full blocks
    assert all(engine.result(r).finished for r in rids)
    core = engine.core
    assert core.trace_counts["decode"] == 1
    # widths: 16 (the chunk) and 8 (tails + short prompts)
    assert core.trace_counts["prefill"] == 2
    assert core.block_pool.trace_counts == {"gather": 1, "scatter": 1}
    bound = math.log2(core.pool.max_seq / 8) + 1
    assert core.trace_counts["prefill"] <= bound


# --------------------------------------------- refcounts / LRU / stress

def test_refcount_pins_and_releases(gpt):
    engine = ServingEngine(gpt, num_slots=1, min_bucket=8, block_len=8)
    p = _prompts(11, (25,))[0]
    engine.serve_batch([p], max_new_tokens=3, max_steps=100)
    cache = engine.core.prefix_cache
    # drained: nothing pinned
    stack = list(cache.root.children.values())
    assert stack, "prompt blocks were inserted"
    while stack:
        n = stack.pop()
        assert n.refcount == 0
        stack.extend(n.children.values())


def test_match_never_covers_last_token(gpt):
    engine = ServingEngine(gpt, num_slots=1, min_bucket=8, block_len=8)
    p = _prompts(12, (32,))[0]                     # exactly 4 blocks
    engine.serve_batch([p], max_new_tokens=3, max_steps=100)
    cache = engine.core.prefix_cache
    # 32 full-block tokens cached, but a repeat may match at most 24:
    # the last token's logits must come from a real prefill
    assert cache.match_length(p) == (32 - 1) // 8 * 8 == 24


def test_eviction_stress_under_oversubscription(gpt):
    """Many shared-prefix requests through few slots and a starved block
    pool: refcounts must pin live paths, eviction must recycle the rest,
    accounting must balance, and every output must stay exact."""
    engine = ServingEngine(gpt, num_slots=2, min_bucket=8, block_len=8,
                           prefix_blocks=6)        # 48 cached tokens max
    prompts = _shared_prefix_prompts(13, 24, (2, 5, 9, 12, 3, 7)) + \
        _prompts(14, (30, 41, 26))
    outs = engine.serve_batch(prompts, max_new_tokens=4, max_steps=1000)
    for p, o in zip(prompts, outs):
        np.testing.assert_array_equal(np.asarray(o.tokens),
                                      _want_tokens(gpt, p, 4))
    pool = engine.core.block_pool
    cache = engine.core.prefix_cache
    assert pool.free_blocks + pool.used_blocks == pool.num_blocks
    assert pool.used_blocks <= pool.num_blocks
    stats = cache.stats()
    assert stats["prefix_evictions"] > 0
    # tree block ownership matches pool accounting exactly
    owned = []
    stack = list(cache.root.children.values())
    while stack:
        n = stack.pop()
        assert n.refcount == 0                      # all requests done
        owned.append(n.block)
        stack.extend(n.children.values())
    assert len(owned) == len(set(owned)) == pool.used_blocks


def test_blockpool_validation_and_accounting():
    with pytest.raises(ValueError, match="divide"):
        BlockPool(num_blocks=4, block_len=10, max_seq=64, num_layers=1,
                  kv_heads=2, head_dim=4)
    pool = BlockPool(num_blocks=2, block_len=8, max_seq=16, num_layers=1,
                     kv_heads=2, head_dim=4)
    a, b = pool.alloc(), pool.alloc()
    assert {a, b} == {0, 1} and pool.free_blocks == 0
    with pytest.raises(RuntimeError, match="exhausted"):
        pool.alloc()
    pool.free(a)
    with pytest.raises(ValueError, match="double free"):
        pool.free(a)


def test_match_release_is_idempotent_and_guarded():
    pool = BlockPool(num_blocks=4, block_len=2, max_seq=8, num_layers=1,
                     kv_heads=1, head_dim=2)
    cache = PrefixCache(pool)

    class _FakeKV:
        ks = [jnp.zeros((1, 8, 1, 2))]
        vs = [jnp.zeros((1, 8, 1, 2))]

    toks = np.arange(6, dtype=np.int32)
    cache.insert(toks, _FakeKV(), 0)
    mr = cache.match(toks)
    assert mr.tokens == 4 and len(mr.blocks) == 2   # (6-1)//2 blocks
    assert all(n.refcount == 1 for n in mr._nodes)
    cache.release(mr)
    cache.release(mr)                               # idempotent
    assert all(n.refcount == 0 for n in mr._nodes)
    mr2 = cache.match(toks)
    cache.release(mr2)
    with pytest.raises(RuntimeError, match="underflow"):
        mr2._released = False
        cache.release(mr2)


# -------------------------------------------------- head-of-line skip

def _req(rid, n, arrival=1.0):
    return Request(request_id=rid, prompt=np.zeros(n, np.int32),
                   max_new_tokens=4, sampling=SamplingParams(),
                   arrival_time=arrival)


def test_budget_validation_rejects_unsatisfiable(gpt):
    """A budget the admission gate can never open would starve every
    request (the over-budget escape sits inside the gate) — both layers
    reject it loudly instead."""
    s = Scheduler(num_slots=2, max_seq=128, min_bucket=16)
    s.submit(_req(0, 10))
    with pytest.raises(ValueError, match="token_budget"):
        s.admit(1, token_budget=0)
    with pytest.raises(ValueError, match="max_prefill_tokens_per_step"):
        ServingEngine(gpt, max_prefill_tokens_per_step=0)


def test_block_len_rounds_down_to_pow2_divisor(gpt):
    """A non-pow2 block_len lands on the largest pow2 divisor <= it, not
    on a degenerate per-token tree."""
    engine = ServingEngine(gpt, num_slots=1, block_len=12)  # max_seq 128
    assert engine.core.block_pool.block_len == 8


def test_admit_skips_oversized_head():
    s = Scheduler(num_slots=4, max_seq=256, min_bucket=16, skip_window=2)
    s.submit(_req(0, 200))                          # bucket 256
    s.submit(_req(1, 10))                           # bucket 16
    out = s.admit(2, token_budget=64)
    assert [r.request_id for r, _ in out] == [1]
    assert s.waiting[0].request_id == 0             # head kept its place
    # with budget for the head, FCFS order resumes
    out = s.admit(2, token_budget=512)
    assert [r.request_id for r, _ in out] == [0]


def test_admit_skip_window_bounds_lookahead():
    """The window bounds how far a fitting request may jump from: with
    skip_window=1 the fit at position 2 is invisible — and since nothing
    else was admitted and the head can NEVER fit the full budget, the
    head goes through over-budget (the budget is a stall bound, not a
    correctness bound) instead of idling the slots forever."""
    s = Scheduler(num_slots=4, max_seq=256, min_bucket=16, skip_window=1)
    for rid, n in enumerate((200, 200, 10)):        # fit is past window
        s.submit(_req(rid, n))
    out = s.admit(2, token_budget=64)
    assert [r.request_id for r, _ in out] == [0]
    assert s.waiting[0].request_id == 1


def test_admit_no_starvation_bound():
    """After max_head_skips jumps the window collapses to the head; a
    head that can never fit the full budget is then admitted over-budget
    — every request gets through in bounded time."""
    s = Scheduler(num_slots=4, max_seq=256, min_bucket=16,
                  skip_window=4, max_head_skips=3)
    s.submit(_req(0, 200))
    for rid in range(1, 10):
        s.submit(_req(rid, 10))
    got = []
    for _ in range(6):
        got += [r.request_id for r, _ in s.admit(1, token_budget=64)]
    # exactly max_head_skips small requests jumped the head, then the
    # head went through (over-budget) and FCFS resumed
    assert got == [1, 2, 3, 0, 4, 5]


def test_engine_budget_admits_small_past_big(gpt):
    """End-to-end: with a per-step prefill token budget, a small prompt
    behind an 8x-bigger head starts decoding first — slots never idle —
    and both outputs stay exact."""
    engine = ServingEngine(gpt, num_slots=2, min_bucket=8, block_len=8,
                           max_prefill_tokens_per_step=32)
    big = _prompts(15, (100,))[0]                   # bucket 128 > 32
    small = _prompts(16, (9,))[0]                   # bucket 16 <= 32
    rid_b = engine.submit(big, max_new_tokens=3)
    rid_s = engine.submit(small, max_new_tokens=3)
    engine.step()
    assert len(engine._requests[rid_s].tokens) >= 1
    assert len(engine._requests[rid_b].tokens) == 0
    engine.run_until_complete(500)
    np.testing.assert_array_equal(
        np.asarray(engine.result(rid_b).tokens), _want_tokens(gpt, big, 3))
    np.testing.assert_array_equal(
        np.asarray(engine.result(rid_s).tokens),
        _want_tokens(gpt, small, 3))
