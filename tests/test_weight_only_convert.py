"""nn.quant.convert_to_weight_only: the LLM weight-only deployment path —
swap Linears for quantized-weight layers and run the model (incl. the
single-scan generate loop) unchanged."""

import numpy as np
import pytest

import jax.numpy as jnp

import paddle_tpu.nn as nn
import paddle_tpu.nn.quant as Q
from paddle_tpu.models import GPTForCausalLM, gpt_tiny


def _grid_weight(rs, shape):
    """A weight that already sits exactly on the int8 per-channel grid
    (each channel's absmax pinned to 127 so absmax requantization
    reproduces the same grid), making quantization LOSSLESS."""
    scale = (rs.rand(shape[1]) + 0.5).astype(np.float32)
    q = rs.randint(-126, 127, shape).astype(np.float32)
    q[0, :] = 127.0
    return q / 127.0 * scale


def test_weight_only_linear_close_and_exact_on_grid():
    rs = np.random.RandomState(0)
    lin = nn.Linear(16, 24)
    x = jnp.asarray(rs.randn(5, 16), jnp.float32)
    wol = Q.WeightOnlyLinear(lin.weight, lin.bias)
    a, b = np.asarray(lin(x)), np.asarray(wol(x))
    assert np.abs(a - b).max() / np.abs(a).max() < 2e-2  # int8 error bound
    # exactness on the int8 grid
    lin.weight = jnp.asarray(_grid_weight(rs, (16, 24)))
    wol2 = Q.WeightOnlyLinear(lin.weight, lin.bias)
    np.testing.assert_allclose(np.asarray(wol2(x)), np.asarray(lin(x)),
                               rtol=1e-4, atol=1e-4)


def test_int4_shapes_and_bound():
    rs = np.random.RandomState(1)
    lin = nn.Linear(16, 24)
    wol = Q.WeightOnlyLinear(lin.weight, lin.bias, weight_dtype="int4")
    assert wol.w_quant.shape == (8, 24)  # nibble-packed along input dim
    x = jnp.asarray(rs.randn(5, 16), jnp.float32)
    a, b = np.asarray(lin(x)), np.asarray(wol(x))
    assert np.abs(a - b).max() / np.abs(a).max() < 0.15  # int4 bound
    with pytest.raises(ValueError, match="weight_dtype"):
        Q.WeightOnlyLinear(lin.weight, lin.bias, weight_dtype="int2")


def test_convert_swaps_all_dense_linears():
    from paddle_tpu.distributed.meta_parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)

    model = GPTForCausalLM(gpt_tiny())
    kinds = (nn.Linear, ColumnParallelLinear, RowParallelLinear)
    n_linear = sum(1 for _, l in model.named_sublayers()
                   if type(l) in kinds)
    assert n_linear > 0
    qm = Q.convert_to_weight_only(model)
    swapped = [l for _, l in qm.named_sublayers()
               if type(l) is Q.WeightOnlyLinear]
    assert len(swapped) == n_linear
    assert all(l.w_quant.dtype == jnp.int8 for l in swapped)
    # embeddings/norms untouched; original model untouched (deepcopy)
    assert sum(1 for _, l in model.named_sublayers()
               if type(l) in kinds) == n_linear


def test_convert_shared_linear_stays_shared():
    """A linear tied into two parent slots converts at BOTH slots to ONE
    shared WeightOnlyLinear (review: named_sublayers dedups by id and
    used to leave the second slot dense)."""

    class Tied(nn.Layer):
        def __init__(self):
            super().__init__()
            lin = nn.Linear(8, 8)
            self.a = lin
            self.b = lin

        def forward(self, x):
            return self.b(self.a(x))

    qm = Q.convert_to_weight_only(Tied())
    assert type(qm.a) is Q.WeightOnlyLinear
    assert qm.a is qm.b  # sharing preserved


def test_convert_bare_linear_and_seq_parallel_subclass():
    lin = nn.Linear(8, 4)
    q = Q.convert_to_weight_only(lin)
    assert type(q) is Q.WeightOnlyLinear  # not a silent no-op

    from paddle_tpu.distributed.meta_parallel.sequence_parallel import (
        ColumnSequenceParallelLinear)

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.p = ColumnSequenceParallelLinear(8, 8)

        def forward(self, x):
            return self.p(x)

    qm = Q.convert_to_weight_only(M())
    assert type(qm.p) is Q.WeightOnlyLinear  # subclass converted too


def test_converted_gpt_generates_and_tracks_fp_scores():
    rs = np.random.RandomState(2)
    model = GPTForCausalLM(gpt_tiny())
    qm = Q.convert_to_weight_only(model)
    ids = jnp.asarray(rs.randint(0, 256, (2, 6)))
    seq, scores = qm.generate(ids, max_new_tokens=4, output_scores=True)
    assert seq.shape == (2, 10)
    _, fp_scores = model.generate(ids, max_new_tokens=4, output_scores=True)
    # first-step scores (same prompt) agree to int8 weight error
    a, b = np.asarray(scores[:, 0]), np.asarray(fp_scores[:, 0])
    rel = np.abs(a - b).max() / max(np.abs(b).max(), 1e-6)
    assert rel < 0.1, rel


def test_converted_model_grid_weights_exact_generation():
    """With every Linear weight ON the int8 grid, conversion is lossless
    and the converted model's greedy generation is token-identical."""
    from paddle_tpu.distributed.meta_parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)

    rs = np.random.RandomState(3)
    model = GPTForCausalLM(gpt_tiny())
    for _, layer in model.named_sublayers():
        if type(layer) in (nn.Linear, ColumnParallelLinear,
                           RowParallelLinear):
            layer.weight = jnp.asarray(
                _grid_weight(rs, tuple(layer.weight.shape)) * 0.05)
    qm = Q.convert_to_weight_only(model)
    ids = jnp.asarray(rs.randint(0, 256, (2, 5)))
    np.testing.assert_array_equal(
        np.asarray(qm.generate(ids, max_new_tokens=5)),
        np.asarray(model.generate(ids, max_new_tokens=5)))


def test_llm_int8_conversion_mode():
    rs = np.random.RandomState(6)
    lin = nn.Linear(16, 24)
    x = jnp.asarray(rs.randn(5, 16), jnp.float32)
    # make one input column an outlier so both paths run
    x = x.at[:, 3].set(20.0)
    m = Q.convert_to_weight_only(nn.Sequential(lin),
                                 weight_dtype="llm.int8", threshold=6.0)
    assert type(m[0]) is Q.LLMInt8Linear
    a, b = np.asarray(lin(x)), np.asarray(m(x))
    assert np.abs(a - b).max() / np.abs(a).max() < 2e-2
    with pytest.raises(ValueError, match="weight_dtype"):
        Q.convert_to_weight_only(lin, weight_dtype="int2")


def test_llm_int8_model_generates():
    rs = np.random.RandomState(7)
    model = GPTForCausalLM(gpt_tiny())
    qm = Q.convert_to_weight_only(model, weight_dtype="llm.int8")
    ids = jnp.asarray(rs.randint(0, 256, (2, 5)))
    seq = qm.generate(ids, max_new_tokens=3)
    assert seq.shape == (2, 8)


def test_llama_weight_only_generates():
    """Llama's bias-free projections convert too; generation runs and the
    first-step scores track fp within int8 error."""
    from paddle_tpu.models import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(vocab_size=128, hidden_size=64, intermediate_size=176,
                      num_layers=2, num_heads=4, num_kv_heads=2,
                      max_seq_len=64)
    model = LlamaForCausalLM(cfg)
    qm = Q.convert_to_weight_only(model)
    n_q = sum(1 for _, l in qm.named_sublayers()
              if type(l) is Q.WeightOnlyLinear)
    assert n_q >= 2 * 7  # q/k/v/o + gate/up/down per layer
    ids = jnp.asarray(np.random.RandomState(8).randint(0, 128, (2, 6)))
    seq, scores = qm.generate(ids, max_new_tokens=3, output_scores=True)
    _, fp = model.generate(ids, max_new_tokens=3, output_scores=True)
    rel = np.abs(np.asarray(scores[:, 0]) - np.asarray(fp[:, 0])).max() / \
        max(float(np.abs(np.asarray(fp[:, 0])).max()), 1e-6)
    assert seq.shape == (2, 9) and rel < 0.1, rel
