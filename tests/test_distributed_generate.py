"""Distributed inference: model.generate and beam_search on a
TP(mp)-sharded model over the virtual mesh — GSPMD partitions the whole
compiled decode scan; outputs must match the dense single-device run
token for token (greedy decoding is float-sensitive only at near-ties,
so the oracle compares SCORES with tolerance and sequences exactly under
matched arithmetic where possible)."""

import numpy as np
import pytest

from conftest import requires_modern_jax

import jax
import jax.numpy as jnp

import paddle_tpu
import paddle_tpu.distributed as dist
from paddle_tpu.models import LlamaForCausalLM, llama_shard_fn, llama_tiny
from paddle_tpu.models.generation import beam_search


def _build(shard):
    paddle_tpu.seed(11)
    cfg = llama_tiny()
    model = LlamaForCausalLM(cfg)
    if shard:
        mesh = dist.ProcessMesh(np.arange(8).reshape(2, 4),
                                dim_names=["dp", "mp"])
        dist.shard_layer(model, mesh, llama_shard_fn(mesh))
    return model


@requires_modern_jax
def test_generate_on_mp_sharded_model_matches_dense():
    ids = jnp.asarray(np.random.RandomState(0).randint(0, 256, (4, 6)))
    dense = _build(False)
    seq_d, sc_d = dense.generate(ids, max_new_tokens=5, output_scores=True)
    sharded = _build(True)
    seq_s, sc_s = sharded.generate(ids, max_new_tokens=5,
                                   output_scores=True)
    # scores: same function, different partitioning -> tolerance
    np.testing.assert_allclose(np.asarray(sc_s), np.asarray(sc_d),
                               rtol=2e-4, atol=2e-4)
    # greedy chains agree unless a near-tie flips a token; verify each
    # sharded token is (near-)argmax under the dense scores
    sd = np.asarray(sc_d)
    toks = np.asarray(seq_s)[:, 6:]
    for bi in range(toks.shape[0]):
        for t in range(toks.shape[1]):
            chosen = sd[bi, t, toks[bi, t]]
            best = sd[bi, t].max()
            assert best - chosen < 1e-3, (bi, t, best - chosen)


@requires_modern_jax
def test_beam_search_on_mp_sharded_model():
    ids = jnp.asarray(np.random.RandomState(1).randint(0, 256, (2, 5)))
    dense = _build(False)
    seq_d, score_d = beam_search(dense, ids, max_new_tokens=4, beam_size=3)
    sharded = _build(True)
    seq_s, score_s = beam_search(sharded, ids, max_new_tokens=4,
                                 beam_size=3)
    np.testing.assert_allclose(np.asarray(score_s), np.asarray(score_d),
                               rtol=2e-3, atol=2e-3)
    assert seq_s.shape == seq_d.shape
