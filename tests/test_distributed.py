"""Distributed core tests on the 8-device CPU mesh.

Model: the reference's single-host distributed tests (SURVEY.md §4) —
test/collective/fleet/hybrid_parallel_mp_layers.py (mp layers vs dense
equivalents), sharding-vs-DP equality, collective API tests
(test/collective/collective_allreduce_api.py).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import paddle_tpu
import paddle_tpu.nn as nn
import paddle_tpu.optimizer as opt
import paddle_tpu.distributed as dist
from paddle_tpu.nn import functional_call, state


@pytest.fixture
def mp_mesh():
    """mp=4 dp=2 hybrid mesh."""
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    dist.fleet.init(is_collective=True, strategy=s)
    yield dist.get_hybrid_communicate_group()
    dist.topology.set_hybrid_communicate_group(None)


def test_topology_comm_lists():
    # dp=2, mp=4 over the 6-axis order (dp, pp, sharding, sep, ep, mp)
    topo = dist.CommunicateTopology(dims=[2, 1, 1, 1, 1, 4])
    assert topo.world_size() == 8
    mp_groups = topo.get_comm_list("mp")
    assert len(mp_groups) == 2 and all(len(g) == 4 for g in mp_groups)
    dp_groups = topo.get_comm_list("dp")
    assert len(dp_groups) == 4 and all(len(g) == 2 for g in dp_groups)
    # ranks partition the world
    assert sorted(sum(mp_groups, [])) == list(range(8))


def test_hcg_mesh_axes(mp_mesh):
    hcg = mp_mesh
    assert hcg.get_model_parallel_world_size() == 4
    assert hcg.get_data_parallel_world_size() == 2
    assert dict(hcg.get_mesh().shape)["mp"] == 4


def test_eager_allreduce_sharded():
    g = dist.new_group(list(range(8)))
    mesh = g.mesh
    x = jnp.arange(8.0)
    xs = jax.device_put(x, NamedSharding(mesh, P(g.name)))
    out = dist.all_reduce(xs, group=g)
    np.testing.assert_allclose(np.asarray(out), np.full(1, 28.0), rtol=1e-6)


def test_eager_allgather_and_reduce_scatter():
    g = dist.new_group(list(range(8)))
    mesh = g.mesh
    x = jnp.arange(16.0).reshape(8, 2)
    xs = jax.device_put(x, NamedSharding(mesh, P(g.name, None)))
    gathered = dist.all_gather(xs, group=g)
    # per-shard [1,2] gathered (tiled) -> [8,2], replicated across the axis
    assert gathered.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(gathered), np.asarray(x))
    rs = dist.reduce_scatter(input=x, group=g)
    # replicated input [8,2]: psum_scatter over 8 'ranks' each holding same
    # -> each shard gets 8 * its slice; shape [8,2] sharded
    assert rs.shape == (8, 2)
    np.testing.assert_allclose(np.asarray(rs), np.asarray(x) * 8)


def test_collectives_inside_shard_map():
    from paddle_tpu.distributed._jax_compat import shard_map
    g = dist.new_group(list(range(8)))
    mesh = g.mesh

    def body(x):
        s = dist.all_reduce(x, group=g)          # psum
        gathered = dist.all_gather(x, group=g)   # [8]
        return s, gathered

    f = shard_map(body, mesh=mesh, in_specs=(P(g.name),),
                  out_specs=(P(), P()), check_vma=False)
    s, gathered = jax.jit(f)(jnp.arange(8.0))
    assert float(s[0]) == 28.0
    np.testing.assert_array_equal(np.asarray(gathered), np.arange(8.0))


def test_column_row_parallel_vs_dense(mp_mesh):
    """The reference's core TP oracle: parallel layers == dense layer."""
    from paddle_tpu.distributed.meta_parallel import (ColumnParallelLinear,
                                                      RowParallelLinear)
    hcg = mp_mesh
    mesh = hcg.get_mesh()
    paddle_tpu.seed(7)
    col = ColumnParallelLinear(16, 32, gather_output=False)
    row = RowParallelLinear(32, 16, input_is_parallel=True)

    class TPBlock(nn.Layer):
        def __init__(self):
            super().__init__()
            self.col = col
            self.row = row

        def forward(self, x):
            return self.row(nn.functional.relu(self.col(x)))

    block = TPBlock()
    params, buffers = state(block)
    from paddle_tpu.distributed.sharding_utils import get_param_specs, shard_state
    specs = get_param_specs(block)
    sharded_params = shard_state(mesh, params, {k: specs.get(k, P()) for k in params})

    x = jnp.asarray(np.random.randn(8, 16).astype(np.float32))

    @jax.jit
    def fwd(p, x):
        out, _ = functional_call(block, p, buffers, (x,))
        return out

    out_tp = fwd(sharded_params, x)

    # dense reference with the same weights
    def dense(x):
        h = np.maximum(np.asarray(x) @ np.asarray(params["col.weight"]) +
                       np.asarray(params["col.bias"]), 0)
        return h @ np.asarray(params["row.weight"]) + np.asarray(params["row.bias"])

    np.testing.assert_allclose(np.asarray(out_tp), dense(x), rtol=5e-4,
                               atol=1e-4)


def test_vocab_parallel_embedding_and_ce(mp_mesh):
    from paddle_tpu.distributed.meta_parallel import (VocabParallelEmbedding,
                                                      parallel_cross_entropy)
    hcg = mp_mesh
    mesh = hcg.get_mesh()
    emb = VocabParallelEmbedding(32, 8)
    params, buffers = state(emb)
    from paddle_tpu.distributed.sharding_utils import get_param_specs, shard_state
    specs = get_param_specs(emb)
    sp = shard_state(mesh, params, {k: specs[k] for k in params})
    ids = jnp.asarray([[0, 5, 31], [7, 8, 9]])

    @jax.jit
    def fwd(p, ids):
        out, _ = functional_call(emb, p, buffers, (ids,))
        return out

    out = fwd(sp, ids)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(params["weight"])[np.asarray(ids)],
        rtol=1e-5)

    # vocab-parallel CE == plain CE
    logits = jnp.asarray(np.random.randn(4, 32).astype(np.float32))
    labels = jnp.asarray([1, 30, 2, 7])
    logits_sharded = jax.device_put(logits, NamedSharding(mesh, P(None, "mp")))

    @jax.jit
    def ce(lg, lb):
        return parallel_cross_entropy(lg, lb)

    got = ce(logits_sharded, labels)
    ref = nn.functional.cross_entropy(logits, labels, reduction="none")
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=2e-4,
                               atol=1e-5)


def test_dp_sharded_batch_equals_serial():
    """DP oracle: global-batch step on dp mesh == single-device step."""
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 8}
    dist.fleet.init(is_collective=True, strategy=s)
    hcg = dist.get_hybrid_communicate_group()
    mesh = hcg.get_mesh()
    try:
        paddle_tpu.seed(3)
        model = nn.Sequential(nn.Linear(4, 8), nn.Tanh(), nn.Linear(8, 2))
        params, buffers = state(model)
        o = opt.SGD(learning_rate=0.1)
        ostate = o.init(params)
        x = np.random.randn(16, 4).astype(np.float32)
        y = (np.arange(16) % 2).astype(np.int64)

        def step(p, os_, xb, yb):
            def loss_fn(p):
                out, _ = functional_call(model, p, buffers, (xb,))
                return nn.functional.cross_entropy(out, jnp.asarray(yb))
            loss, g = jax.value_and_grad(loss_fn)(p)
            newp, nos = o.update(g, os_, p)
            return newp, nos, loss

        # serial
        p1, os1, loss1 = jax.jit(step)(params, ostate, jnp.asarray(x), jnp.asarray(y))
        # dp: same global batch, sharded over dp
        xb = jax.device_put(jnp.asarray(x), NamedSharding(mesh, P("dp")))
        yb = jax.device_put(jnp.asarray(y), NamedSharding(mesh, P("dp")))
        p2, os2, loss2 = jax.jit(step)(params, ostate, xb, yb)
        np.testing.assert_allclose(float(loss1), float(loss2), rtol=1e-5)
        for k in p1:
            np.testing.assert_allclose(np.asarray(p1[k]), np.asarray(p2[k]),
                                       rtol=1e-4, atol=1e-6)
    finally:
        dist.topology.set_hybrid_communicate_group(None)


def test_zero_sharding_specs():
    from paddle_tpu.distributed.meta_parallel import build_sharded_specs
    param_specs = {"w": P(None, "mp"), "b": P()}
    shapes = {"w": (16, 32), "b": (32,)}
    p, g, s = build_sharded_specs(param_specs, shapes, level="os",
                                  degree=8)
    # slots sharded over 'sharding' on first free divisible dim
    assert s["w"] == P("sharding", "mp")
    assert s["b"] == P("sharding")
    # stage1: params/grads untouched
    assert p["w"] == P(None, "mp") and g["w"] == P(None, "mp")
    p3, g3, s3 = build_sharded_specs(param_specs, shapes, level="p_g_os",
                                     degree=8)
    assert p3["w"] == P("sharding", "mp")


def test_zero1_opt_state_sharded_end_to_end():
    """ZeRO-1: jitted step with sharded opt-state out_shardings matches
    unsharded results (the reference's sharding-vs-DP loss equality)."""
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 1, "sharding_degree": 8}
    dist.fleet.init(is_collective=True, strategy=s)
    hcg = dist.get_hybrid_communicate_group()
    mesh = hcg.get_mesh()
    try:
        model = nn.Linear(8, 8)
        params, buffers = state(model)
        base = opt.AdamW(learning_rate=0.01)
        sharded_opt = dist.fleet.distributed_optimizer(base)
        ostate = sharded_opt.init(params)
        from paddle_tpu.distributed.sharding_utils import get_param_specs
        pspecs = {k: P() for k in params}
        shapes = {k: tuple(v.shape) for k, v in params.items()}
        sspecs = sharded_opt.state_specs(pspecs, shapes)
        # lay out opt state sharded
        from paddle_tpu.distributed.sharding_utils import shard_state
        ostate_sharded = {
            "step": ostate["step"],
            "slots": {k: {sl: jax.device_put(v, NamedSharding(mesh, sspecs["slots"][k]))
                          for sl, v in slots.items()}
                      for k, slots in ostate["slots"].items()},
            "master": ostate["master"],
        }
        x = jnp.asarray(np.random.randn(4, 8).astype(np.float32))
        y = jnp.asarray(np.random.randn(4, 8).astype(np.float32))

        def step(p, os_):
            def loss_fn(p):
                out, _ = functional_call(model, p, buffers, (x,))
                return jnp.mean((out - y) ** 2)
            loss, g = jax.value_and_grad(loss_fn)(p)
            return sharded_opt.update(g, os_, p)

        p_ref, os_ref = jax.jit(step)(params, ostate)
        p_sh, os_sh = jax.jit(step)(params, ostate_sharded)
        np.testing.assert_allclose(np.asarray(p_ref["weight"]),
                                   np.asarray(p_sh["weight"]), rtol=1e-5,
                                   atol=1e-6)
        # sharded slot layout preserved in output
        m1 = os_sh["slots"]["weight"]["moment1"]
        assert isinstance(m1.sharding, NamedSharding)
    finally:
        dist.topology.set_hybrid_communicate_group(None)


def test_reduce_scatter_max_and_avg():
    # op was previously ignored (always SUM) — code-review r2 fix
    import paddle_tpu.distributed as dist_mod
    g = dist_mod.collective.new_group(list(range(4)))
    mesh = g.mesh
    vals = np.arange(16, dtype=np.float32).reshape(4, 4)
    x = jax.make_array_from_callback(
        (16,), NamedSharding(mesh, P(g.name)),
        lambda idx: vals[idx[0].start // 4])
    out_max = dist_mod.collective.reduce_scatter(input=x, op="max", group=g)
    # each rank's tile_r = max over ranks of their r-th tile; global view:
    got = np.asarray(out_max)
    want = vals.reshape(4, 4, 1).max(axis=0).reshape(-1)[
        np.arange(4)]  # tile size 1 per rank? shape (16//4)=4 per rank
    # simpler: reconstruct expected per-rank tiles
    tiles = vals.reshape(4, 4, 1)  # [rank, tile, 1] with tile size 1
    expect = vals.reshape(4, 4).max(axis=0)  # max over ranks per position
    np.testing.assert_allclose(got, expect)
    out_avg = dist_mod.collective.reduce_scatter(input=x, op="avg", group=g)
    np.testing.assert_allclose(np.asarray(out_avg),
                               vals.mean(axis=0))


def test_eager_collective_cache_respects_new_mesh():
    # cache key must include the mesh: same group name/id over a different
    # device set must not reuse the stale shard_map (code-review r2 fix)
    import paddle_tpu.distributed as dist_mod
    g2 = dist_mod.collective.new_group([0, 1])
    x2 = jax.make_array_from_callback(
        (2,), NamedSharding(g2.mesh, P(g2.name)),
        lambda idx: np.asarray([float(idx[0].start) + 1.0], np.float32))
    out2 = dist_mod.collective.all_reduce(x2, group=g2)
    assert float(np.asarray(out2.addressable_shards[0].data)[0]) == 3.0
    g4 = dist_mod.collective.new_group([0, 1, 2, 3])
    x4 = jax.make_array_from_callback(
        (4,), NamedSharding(g4.mesh, P(g4.name)),
        lambda idx: np.asarray([1.0], np.float32))
    out4 = dist_mod.collective.all_reduce(x4, group=g4)
    assert float(np.asarray(out4.addressable_shards[0].data)[0]) == 4.0


def test_distributed_api_surface_round3():
    """Round-3 paddle.distributed completions: gather, object collectives,
    group management, stream namespace, ParallelEnv, split."""
    import numpy as np
    import paddle_tpu.distributed as dist

    # gather: shards land in the list
    g = dist.new_group(list(range(8)))
    x = jnp.arange(8.0)
    from jax.sharding import NamedSharding, PartitionSpec as P
    xs = jax.device_put(x, NamedSharding(g.mesh, P(g.name)))
    parts = dist.gather(xs, group=g)
    assert len(parts) == 8
    np.testing.assert_allclose(np.concatenate([np.asarray(p) for p in parts]),
                               np.arange(8.0))

    # object collectives (single-controller semantics)
    objs = ["a", "b"]
    assert dist.broadcast_object_list(objs) == ["a", "b"]
    out = []
    dist.scatter_object_list(out, ["only"])
    assert out == ["only"]

    # group management
    assert dist.get_backend() == "XLA"
    assert dist.get_group(g.id) is g
    dist.destroy_process_group(g)
    assert dist.get_group(g.id) is not g

    # stream namespace aliases the sync collectives
    assert dist.stream.all_reduce is dist.all_reduce

    # ParallelEnv
    env = dist.ParallelEnv()
    assert env.rank == 0 and env.world_size >= 1
    assert isinstance(env.trainer_endpoints, list)

    # p2p stance: isend/irecv raise the same shard_map/ppermute guidance
    import pytest as _pytest
    with _pytest.raises(RuntimeError, match="ppermute"):
        dist.isend(jnp.zeros(2), 1)

    # save/load re-exports
    assert dist.save_state_dict is not None
    assert dist.load_state_dict is not None


def test_distributed_split_shim():
    """paddle.distributed.split: column/row-parallel linear + vocab
    embedding factory with param reuse across calls."""
    import numpy as np
    import paddle_tpu
    import paddle_tpu.distributed as dist
    s = dist.DistributedStrategy()
    s.hybrid_configs = {"mp_degree": 2, "dp_degree": 4}
    dist.fleet.init(is_collective=True, strategy=s)
    try:
        paddle_tpu.seed(0)
        x = jnp.ones((2, 8))
        y1 = dist.split(x, (8, 6), operation="linear", axis=1,
                        name="col1")
        y2 = dist.split(x, (8, 6), operation="linear", axis=1,
                        name="col1")      # cached layer -> same params
        assert y1.shape == (2, 6)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2))
        ids = jnp.asarray(np.arange(4).reshape(2, 2))
        e = dist.split(ids, (16, 8), operation="embedding", name="emb1")
        assert e.shape == (2, 2, 8)
    finally:
        dist.topology.set_hybrid_communicate_group(None)
