"""Round-4 fourth sweep: Weibull/LKJCholesky distributions, VisualDL and
Wandb callbacks, sysconfig, utils.require_version, the legacy
utils.profiler shim, and paddle.callbacks top-level wiring.

Oracles: closed-form moments and densities (Weibull integral == 1, LKJ
d=2 uniform-correlation facts), real Model.fit logging for VisualDL.
"""

import json
import os
import tempfile

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.distribution import Weibull, LKJCholesky


class TestWeibull:
    def test_moments_and_density(self):
        w = Weibull(2.0, 1.5)
        s = np.asarray(w.sample((20000,)))
        assert abs(s.mean() - float(w.mean)) < 0.05
        assert abs(s.var() - float(w.variance)) < 0.15
        xs = np.linspace(1e-3, 12, 4000)
        p = np.exp(np.asarray(w.log_prob(jnp.asarray(xs))))
        assert abs(np.trapezoid(p, xs) - 1) < 1e-3

    def test_edge_values_and_gradients(self):
        # x == 0 with k == 1 is the exponential density at 0: log(1/lam)
        w1 = Weibull(2.0, 1.0)
        assert float(w1.log_prob(jnp.asarray(0.0))) == pytest.approx(
            -np.log(2.0))
        assert float(Weibull(2.0, 2.0).log_prob(jnp.asarray(0.0))) == -np.inf
        # negative support: -inf value AND finite (zero) gradient — the
        # unselected log(z) branch must not poison grads
        import jax
        g = jax.grad(lambda x: jnp.where(
            jnp.isfinite(w1.log_prob(x)), w1.log_prob(x), 0.0))(
                jnp.asarray(-1.0))
        assert np.isfinite(float(g))

    def test_support_and_entropy(self):
        w = Weibull(1.0, 2.0)
        assert float(w.log_prob(jnp.asarray(-0.5))) == -np.inf
        # k=1 reduces to Exponential(1/lambda): entropy = 1 + ln(lambda)
        e = Weibull(3.0, 1.0)
        assert float(e.entropy()) == pytest.approx(1 + np.log(3.0), rel=1e-5)


class TestLKJCholesky:
    def test_d2_eta1_uniform_correlation(self):
        l = LKJCholesky(2, 1.0)
        L = np.asarray(l.sample((20000,)))
        np.testing.assert_allclose((L ** 2).sum(-1), 1.0, atol=1e-5)
        r = L[:, 1, 0]
        assert abs(r.var() - 1 / 3) < 0.02        # r ~ U(-1, 1)
        # analytic density: p(r) = 1/2 -> log_prob = -ln 2
        assert float(l.log_prob(jnp.asarray(L[0]))) == pytest.approx(
            -np.log(2), abs=1e-5)

    def test_d2_eta2_variance(self):
        # p(r) \propto (1 - r^2)^{eta-1}: Var(r) = 1/(2 eta + 1)
        r = np.asarray(LKJCholesky(2, 2.0).sample((20000,)))[:, 1, 0]
        assert abs(r.var() - 0.2) < 0.02

    def test_d3_marginal_correlation_variance(self):
        # known LKJ fact: a single correlation's marginal density is
        # p(r) \propto (1 - r^2)^(eta - 1 + (d-2)/2), so
        # Var(r) = 1 / (2*(eta + (d-2)/2) + 1); for d=3, eta=1 -> 1/4.
        # This is the oracle that catches wrong per-row Beta parameters
        # in the onion sampler (rows beyond the first).
        L = np.asarray(LKJCholesky(3, 1.0).sample((30000,)))
        corr = L @ np.swapaxes(L, -1, -2)
        for (i, j) in ((1, 0), (2, 0), (2, 1)):
            assert abs(corr[:, i, j].var() - 0.25) < 0.02, (i, j)

    def test_cvine_rejected_not_silently_swapped(self):
        with pytest.raises(NotImplementedError):
            LKJCholesky(3, sample_method="cvine")

    def test_d4_valid_choleskys(self):
        l = LKJCholesky(4, 1.5)
        L = np.asarray(l.sample((500,)))
        np.testing.assert_allclose((L ** 2).sum(-1), 1.0, atol=1e-4)
        corr = L @ np.swapaxes(L, -1, -2)
        np.testing.assert_allclose(
            np.diagonal(corr, axis1=-2, axis2=-1), 1.0, atol=1e-4)
        # positive diagonal (proper cholesky) and finite density
        assert (np.diagonal(L, axis1=-2, axis2=-1) > 0).all()
        assert np.isfinite(np.asarray(l.log_prob(jnp.asarray(L)))).all()

    def test_validation(self):
        with pytest.raises(ValueError):
            LKJCholesky(1)
        with pytest.raises(NotImplementedError):
            LKJCholesky(3, sample_method="nope")


class TestCallbacks:
    def _fit(self, cb):
        import paddle_tpu.nn as nn
        from paddle_tpu.io import TensorDataset
        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        model = paddle.Model(net)
        model.prepare(
            paddle.optimizer.Adam(learning_rate=0.01,
                                  parameters=net.parameters()),
            nn.CrossEntropyLoss(), paddle.metric.Accuracy())
        rng = np.random.RandomState(0)
        ds = TensorDataset([jnp.asarray(rng.randn(32, 4).astype("float32")),
                            jnp.asarray(rng.randint(0, 2, (32, 1)))])
        model.fit(ds, epochs=2, batch_size=16, verbose=0, callbacks=[cb])

    def test_visualdl_logs_fit_scalars(self):
        with tempfile.TemporaryDirectory() as d:
            self._fit(paddle.callbacks.VisualDL(log_dir=d))
            lines = [json.loads(l)
                     for l in open(os.path.join(d, "scalars.jsonl"))]
        assert lines
        tags = {l["tag"] for l in lines}
        assert any(t.startswith("train/") for t in tags)
        assert any(t.startswith("train_epoch/") for t in tags)
        assert all(np.isfinite(l["value"]) for l in lines)
        steps = [l["step"] for l in lines if l["tag"] == "train/loss"]
        assert steps == sorted(steps)

    def test_wandb_raises_with_guidance(self):
        with pytest.raises(ImportError, match="VisualDL"):
            paddle.callbacks.WandbCallback(project="p")


class TestSysconfigAndUtils:
    def test_sysconfig_paths(self):
        lib = paddle.sysconfig.get_lib()
        assert os.path.basename(lib) == "lib"
        # the native pieces actually live there
        assert os.path.isdir(lib)
        assert os.path.basename(paddle.sysconfig.get_include()) == "include"

    def test_require_version(self):
        paddle.utils.require_version("0.1.0")
        paddle.utils.require_version("0.1", "9.9")
        with pytest.raises(RuntimeError):
            paddle.utils.require_version("99.0")
        with pytest.raises(RuntimeError):
            paddle.utils.require_version("0.0.1", "0.0.2")
        with pytest.raises(ValueError):
            paddle.utils.require_version("abc")
        with pytest.raises(ValueError):
            paddle.utils.require_version("")

    def test_legacy_profiler_shim(self):
        paddle.utils.profiler.start_profiler()
        _ = paddle.ones([4]) * 2
        with tempfile.TemporaryDirectory() as d:
            path = os.path.join(d, "trace.json")
            paddle.utils.profiler.stop_profiler(profile_path=path)
            assert os.path.exists(path)
            json.load(open(path))           # valid chrome-trace JSON


class TestReviewRegressions4:
    def test_require_version_prefix_padding(self):
        # "0.2" must accept installed 0.2.x (zero-padded comparison)
        import paddle_tpu
        major_minor = ".".join(paddle_tpu.__version__.split(".")[:2])
        paddle.utils.require_version("0.1", major_minor)

    def test_lkj_rejects_batched_concentration(self):
        with pytest.raises(ValueError, match="scalar concentration"):
            LKJCholesky(3, jnp.asarray([1.0, 2.0]))

    def test_scalar_helper_handles_odd_metric_values(self):
        from paddle_tpu.hapi.callbacks import _scalar
        assert _scalar(1.5) == 1.5
        assert _scalar([2.0]) == 2.0
        assert _scalar(np.float32(3.0)) == 3.0
        assert _scalar([]) is None
        assert _scalar("nan-ish-string") is None
        assert _scalar(np.asarray(4.0)) == 4.0
