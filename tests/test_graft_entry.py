"""The driver's own gates, exercised in CI: dryrun_multichip compiles and
runs the FULL hybrid train step on virtual meshes — including 16 devices
(dp2 x mp2 x pp2 x sharding2), one size beyond the suite's standard
8-device mesh, so topology construction generalizes past the default."""

import os
import subprocess
import sys

import pytest

from conftest import requires_modern_jax

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@requires_modern_jax
@pytest.mark.parametrize("n", [8, 16])
def test_dryrun_multichip(n):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/paddle_tpu_jax_cache")
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import __graft_entry__ as g\n"
        "g.dryrun_multichip(%d)\n"
        "print('DRYRUN_OK', %d)\n" % (REPO, n, n))
    r = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, (r.stdout[-500:], r.stderr[-800:])
    assert f"DRYRUN_OK {n}" in r.stdout
