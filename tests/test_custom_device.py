"""Custom-device plugin registry tests.

Reference test model: test/custom_runtime — installs a fake CustomDevice
plugin (CPU masquerading as a device) and drives the discovery +
placement surface end-to-end (SURVEY.md §4 fixtures).  Here the fake
plugin is the CPU platform registered under a custom type name; a real
out-of-tree backend would instead ship a PJRT plugin whose platform name
is registered the same way (see paddle_tpu/device/custom.py stance).
"""

import numpy as np
import pytest
import jax

import paddle_tpu as paddle
from paddle_tpu.device import custom as C


@pytest.fixture
def fake_dev():
    C.register_custom_device("fake_dev", "cpu")
    yield "fake_dev"
    C.unregister_custom_device("fake_dev")


class TestRegistry:
    def test_discovery_surface(self, fake_dev):
        assert "fake_dev" in paddle.device.get_all_custom_device_type()
        assert paddle.device.is_compiled_with_custom_device("fake_dev")
        assert not paddle.device.is_compiled_with_custom_device("absent")
        assert paddle.device.custom_device_count("fake_dev") == \
            len(jax.devices("cpu"))
        assert paddle.device.custom_device_count("absent") == 0

    def test_unregister(self):
        C.register_custom_device("tmp_dev", "cpu")
        C.unregister_custom_device("tmp_dev")
        assert "tmp_dev" not in C.get_all_custom_device_type()
        # unregistering twice is a no-op, not an error
        C.unregister_custom_device("tmp_dev")

    def test_default_platform_is_type_name(self):
        C.register_custom_device("cpu")          # platform name == type
        try:
            assert C.is_compiled_with_custom_device("cpu")
        finally:
            C.unregister_custom_device("cpu")

    def test_bad_name_rejected(self):
        with pytest.raises(ValueError):
            C.register_custom_device("")


class TestCustomPlace:
    def test_token_and_equality(self, fake_dev):
        p = paddle.CustomPlace("fake_dev", 1)
        assert p == paddle.CustomPlace("fake_dev", 1)
        assert p != paddle.CustomPlace("fake_dev", 0)
        assert "fake_dev" in repr(p)

    def test_resolve_to_jax_device(self, fake_dev):
        d = C.resolve(paddle.CustomPlace("fake_dev", 0))
        assert d is jax.devices("cpu")[0]
        # string form, reference 'type:id' style
        d1 = C.resolve("fake_dev:1")
        assert d1 is jax.devices("cpu")[1]

    def test_unknown_type_errors_with_registry_hint(self):
        with pytest.raises(ValueError, match="register"):
            C.resolve(paddle.CustomPlace("never_registered", 0))

    def test_out_of_range_id(self, fake_dev):
        n = len(jax.devices("cpu"))
        with pytest.raises(ValueError, match="out of range"):
            C.resolve(paddle.CustomPlace("fake_dev", n))

    def test_placement_end_to_end(self, fake_dev):
        """Computation actually lands on the resolved device — the fake
        plugin runs a real op, the reference test/custom_runtime oracle."""
        dev = C.resolve("fake_dev:1")
        x = jax.device_put(np.arange(8.0, dtype=np.float32), dev)
        y = paddle.mean(x)
        assert float(y) == 3.5
        assert list(x.devices())[0] is dev
