"""bench.py gpt_decode CPU-smoke hbm_bw_util projection (ISSUE 5
satellite): the projection must actually fire off a stub evidence file —
BENCH_r05 shipped ``"hbm_bw_util": null`` with no ``bw_note`` because
the old import-based path failed silently.

Named ``test_zz_*`` ON PURPOSE: this container's jaxlib-0.4 pin has the
timing-dependent CPU crasher conftest.py documents (dispatch race after
the ring-attention shard_map tests → nondeterministic NaN/segfault in
LATER tests), and inserting any extra work between the distributed files
measurably raises its hit rate — an early-alphabet placement of this
file reproducibly tripped it in test_dist_checkpoint.  Sorting last
keeps the fragile window byte-identical to the pre-PR suite order."""

import json
import math
from pathlib import Path

import bench


def _stub_evidence(tmp_path: Path, tps=12000.0) -> Path:
    p = tmp_path / "EVIDENCE.json"
    p.write_text(json.dumps({
        "secondary_tpu": {"gpt_decode": {"decode_tokens_per_sec": tps}},
    }))
    return p


def test_projection_fires_from_stub_evidence(tmp_path):
    util, note = bench.decode_bw_projection(str(_stub_evidence(tmp_path)))
    assert util is not None and util > 0
    assert note and "projected" in note and "EVIDENCE.json" in note


def test_projection_matches_byte_model(tmp_path):
    """The projected figure is exactly decode_bw_util at the flagship
    shape — no drift between the two paths."""
    tps = 10000.0
    util, _ = bench.decode_bw_projection(str(_stub_evidence(tmp_path, tps)))
    import jax.numpy as jnp
    from paddle_tpu.models import GPTConfig
    fd = bench.FLAGSHIP_DECODE
    cfg = GPTConfig(vocab_size=fd["vocab"], hidden_size=fd["hidden"],
                    num_layers=fd["layers"], num_heads=fd["heads"],
                    max_seq_len=fd["max_seq"], dtype=fd["dtype"])
    expect = bench.decode_bw_util(
        tps, fd["batch"], fd["prompt"], fd["new"], cfg.num_params(),
        cfg.num_layers, cfg.hidden_size,
        jnp.dtype(cfg.dtype).itemsize, "v5e")
    assert math.isclose(util, expect)


def test_projection_absent_evidence_degrades(tmp_path):
    util, note = bench.decode_bw_projection(str(tmp_path / "missing.json"))
    assert util is None and note is None


def test_projection_malformed_row_degrades(tmp_path):
    p = tmp_path / "EVIDENCE.json"
    p.write_text(json.dumps({"secondary_tpu": {"gpt_decode": {}}}))
    util, note = bench.decode_bw_projection(str(p))
    assert util is None and note is None


def test_projection_structurally_malformed_evidence_degrades(tmp_path):
    """A top-level list / non-dict rows / non-numeric tps (a truncated
    or partial evidence rewrite) must degrade to (None, None) — not
    raise into the caller and wipe out the whole secondary bench."""
    for payload in ("[]", '"junk"', '{"secondary_tpu": []}',
                    '{"secondary_tpu": {"gpt_decode": '
                    '{"decode_tokens_per_sec": "fast"}}}'):
        p = tmp_path / "EVIDENCE.json"
        p.write_text(payload)
        util, note = bench.decode_bw_projection(str(p))
        assert util is None and note is None, payload


def test_projection_fires_from_committed_evidence():
    """The repo's real BENCH_TPU_EVIDENCE.json (present per ISSUE 5) must
    produce a non-null projection — the exact regression BENCH_r05 hit."""
    util, note = bench.decode_bw_projection()
    assert util is not None and util > 0, \
        "committed evidence present but projection still null"
    assert note and "BENCH_TPU_EVIDENCE.json" in note
