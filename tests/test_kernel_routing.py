"""Empirical Pallas-vs-XLA routing (round-3 VERDICT item 1: the default
path must be the measured winner per kernel and shape)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu
from paddle_tpu.core.flags import flags
from paddle_tpu.kernels.routing import MEASURED, use_pallas


def test_rules_agree_with_measurements():
    """Every measured row's routed choice must be the faster side (>= 1.0
    speedup for pallas-chosen rows, <= 1.02 for xla-chosen ones — ties go
    to XLA)."""
    for (kernel, shape), speedup in MEASURED.items():
        if kernel == "flash_attention":
            chosen = use_pallas(kernel, seq_q=shape, seq_k=shape)
        elif kernel == "decode_attention":
            chosen = use_pallas(kernel, kv_len=shape)
        elif kernel in ("layer_norm", "rms_norm"):
            chosen = use_pallas(kernel, rows=shape[0], h=shape[1])
        else:
            chosen = use_pallas(kernel, n=shape)
        if chosen:
            assert speedup >= 1.0, (kernel, shape, speedup)
        else:
            assert speedup <= 1.02, (kernel, shape, speedup)


def test_flash_seq_threshold():
    assert not use_pallas("flash_attention", seq_q=1024, seq_k=1024)
    assert use_pallas("flash_attention", seq_q=2048, seq_k=2048)
    assert use_pallas("flash_attention", seq_q=8192, seq_k=8192)


def test_decode_kv_threshold():
    assert use_pallas("decode_attention", kv_len=4096)
    assert not use_pallas("decode_attention", kv_len=8192)


def test_norms_route_to_xla():
    assert not use_pallas("layer_norm", rows=8192, h=4096)
    assert not use_pallas("rms_norm", rows=8192, h=4096)


def test_routing_mode_overrides():
    old = flags.pallas_routing
    try:
        flags.pallas_routing = "always"
        assert use_pallas("layer_norm", rows=8, h=128)
        flags.pallas_routing = "never"
        assert not use_pallas("flash_attention", seq_q=8192, seq_k=8192)
    finally:
        flags.pallas_routing = old


def test_decode_auto_reference_parity():
    """The dense routed fallback matches the kernel's semantics exactly
    (variable lengths + causal tail + GQA)."""
    from paddle_tpu.kernels.decode_attention import (
        decode_attention, decode_attention_reference)
    rs = np.random.RandomState(0)
    b, sq, h, kh, d, T = 2, 4, 8, 4, 32, 64
    q = jnp.asarray(rs.randn(b, sq, h, d), jnp.float32)
    kc = jnp.asarray(rs.randn(b, T, kh, d), jnp.float32)
    vc = jnp.asarray(rs.randn(b, T, kh, d), jnp.float32)
    lens = jnp.asarray([17, 64], jnp.int32)
    out_k = decode_attention(q, kc, vc, lens, interpret=True)
    out_r = decode_attention_reference(q, kc, vc, lens)
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(out_r),
                               rtol=2e-5, atol=2e-5)


def test_decode_auto_routes_long_cache_to_reference(monkeypatch):
    """On a non-CPU backend the auto wrapper must take the dense path for
    kv > 6144; on CPU it always uses the (interpreted) kernel."""
    import importlib
    da_mod = importlib.import_module("paddle_tpu.kernels.decode_attention")
    calls = []
    monkeypatch.setattr(
        da_mod, "decode_attention_reference",
        lambda *a, **k: calls.append("ref") or jnp.zeros((1, 1, 1, 1)))
    monkeypatch.setattr(
        da_mod, "decode_attention",
        lambda *a, **k: calls.append("kernel") or jnp.zeros((1, 1, 1, 1)))
    monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
    q = jnp.zeros((1, 1, 1, 32))
    kc = jnp.zeros((1, 8192, 1, 32))
    da_mod.decode_attention_auto(q, kc, kc, jnp.zeros((1,), jnp.int32))
    assert calls == ["ref"]
    kc_small = jnp.zeros((1, 4096, 1, 32))
    da_mod.decode_attention_auto(q, kc_small, kc_small,
                                 jnp.zeros((1,), jnp.int32))
    assert calls == ["ref", "kernel"]


def test_fused_adamw_large_tensor_block_cap():
    """Block auto-pick shrinks for very large tensors (the 64M 8192-row
    tile blew Mosaic scoped vmem on chip) but correctness is unchanged."""
    from paddle_tpu.kernels import fused_adamw_update
    rs = np.random.RandomState(1)
    n = 256 * 1024
    p = jnp.asarray(rs.randn(n), jnp.float32)
    g = jnp.asarray(rs.randn(n), jnp.float32)
    m = jnp.zeros(n)
    v = jnp.zeros(n)
    p2, m2, v2 = fused_adamw_update(p, g, m, v, 1, 1e-3, interpret=True)
    ref_m = 0.1 * g
    ref_v = 0.001 * g * g
    ref_p = p - 1e-3 * (ref_m / (1 - 0.9)
                        / (jnp.sqrt(ref_v / (1 - 0.999)) + 1e-8))
    np.testing.assert_allclose(np.asarray(p2), np.asarray(ref_p),
                               rtol=1e-6, atol=1e-6)


def test_norm_block_picker_vmem_cap():
    """h=8192 must pick a block with block*h*4B <= 4MiB (the r4 sweep's
    scoped-vmem failure mode) instead of an illegal large block."""
    from paddle_tpu.kernels.fused_norm import _flatten_and_pick_block
    x = jnp.zeros((4096, 8192), jnp.bfloat16)
    _, block = _flatten_and_pick_block(x)
    assert block > 0
    assert block * 8192 * 4 <= 4 * 1024 * 1024
    x2 = jnp.zeros((8192, 4096), jnp.bfloat16)
    _, block2 = _flatten_and_pick_block(x2)
    assert block2 == 256          # unchanged for the standard shape
