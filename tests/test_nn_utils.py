"""paddle.nn.utils (reference: python/paddle/nn/utils/ — parameter vector
transforms, weight/spectral norm hooks, grad clipping)."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

import paddle_tpu
import paddle_tpu.nn as nn
from paddle_tpu.nn.utils import (parameters_to_vector, vector_to_parameters,
                                 clip_grad_norm_, clip_grad_value_,
                                 weight_norm, remove_weight_norm,
                                 spectral_norm)


def test_parameter_vector_roundtrip():
    ps = [jnp.ones((2, 3)), jnp.arange(4.0)]
    v = parameters_to_vector(ps)
    assert v.shape == (10,)
    back = vector_to_parameters(v, ps)
    for a, b in zip(back, ps):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_clip_grad_norm_and_value():
    g = {"a": jnp.asarray([3.0, 4.0])}
    clipped, total = clip_grad_norm_(g, max_norm=1.0)
    np.testing.assert_allclose(float(total), 5.0, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(clipped["a"]),
                               [0.6, 0.8], rtol=1e-4)
    cv = clip_grad_value_({"a": jnp.asarray([-2.0, 0.5])}, 1.0)
    np.testing.assert_allclose(np.asarray(cv["a"]), [-1.0, 0.5])


def test_weight_norm_preserves_function_and_removes():
    paddle_tpu.seed(0)
    lin = nn.Linear(4, 3)
    w0 = np.asarray(lin.weight)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 4), jnp.float32)
    y0 = np.asarray(lin(x))
    weight_norm(lin, name="weight", dim=0)
    assert "weight_v" in lin._parameters and "weight_g" in lin._parameters
    y1 = np.asarray(lin(x))
    np.testing.assert_allclose(y1, y0, rtol=1e-5, atol=1e-6)
    remove_weight_norm(lin)
    np.testing.assert_allclose(np.asarray(lin.weight), w0, rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(lin(x)), y0, rtol=1e-5,
                               atol=1e-6)


def test_spectral_norm_bounds_sigma():
    paddle_tpu.seed(1)
    lin = nn.Linear(6, 6)
    # scale the weight up so sigma >> 1
    lin._parameters["weight"] = lin.weight * 10.0
    spectral_norm(lin, name="weight", n_power_iterations=5)
    x = jnp.asarray(np.random.RandomState(1).randn(2, 6), jnp.float32)
    for _ in range(5):
        lin(x)                       # power iterations refine u/v
    w_eff = np.asarray(lin._parameters["weight"])
    s = np.linalg.svd(w_eff, compute_uv=False)
    assert s.max() < 1.2             # spectral norm ~1
