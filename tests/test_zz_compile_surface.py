"""Runtime/static consistency gate for graftprog (ISSUE 16).

graftprog (tools/analysis/compile_surface.py) statically enumerates the
serving engine's compile surface and pins it on the program manifest:
``{chunk} + O(log2) prefill buckets + ONE decode + 1 gather + 1
scatter`` per device plane.  This test closes the loop from the OTHER
side: it runs a warm CPU-smoke engine per config leg — tp=1 composed,
tp=1 fused, tp=2 composed — and asserts the trace counters the engine
actually ticked are a SUBSET of what the manifest enumerates, with the
static upper bounds respected.  Manifest drift (a new counter the
analysis missed, a bound the runtime exceeded) fails loudly with the
offending program named.

zz-prefixed for the same reason as test_zz_decode_block /
test_zz_tp_serving: the tp=2 leg drives shard_map on the 8-device CPU
mesh, and the jaxlib-0.4 dispatch-race window conftest documents makes
early-alphabet placement of distributed work reproducibly fragile —
sort after the window.
"""

import math

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.serving import ServingEngine

ENGINE_PLANE = "paddle_tpu.serving.engine.EngineCore"
MAX_SEQ = 64
MIN_BUCKET = 8
# chunk program + pow2 bucket tails: the static "O(log2) shape buckets"
# bound, made concrete for this config
MAX_PREFILL = int(math.log2(MAX_SEQ // MIN_BUCKET)) + 2


@pytest.fixture(scope="module")
def engine_plane():
    """The statically-derived EngineCore counter plane, built through
    the same library entry point the CLI's ``--manifest`` uses."""
    from paddle_tpu.tools.analysis import build_manifest_for_paths
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    scope = [os.path.join(root, p)
             for p in ("paddle_tpu", "bench.py", "scripts")]
    manifest = build_manifest_for_paths(scope, root=root)
    assert ENGINE_PLANE in manifest["planes"], (
        f"manifest lost the EngineCore plane; planes="
        f"{sorted(manifest['planes'])}")
    return manifest["planes"][ENGINE_PLANE]


def _fresh_gpt(seed=0):
    paddle_tpu.seed(seed)
    m = GPTForCausalLM(gpt_tiny())
    m.eval()
    return m


def _run_leg(**engine_kw):
    """Warm CPU smoke: mixed-length prompts, then a resubmitted copy so
    the prefix cache exercises the gather AND scatter programs."""
    eng = ServingEngine(_fresh_gpt(), num_slots=4, max_seq=MAX_SEQ,
                        min_bucket=MIN_BUCKET, prefill_chunk=16,
                        block_len=16, **engine_kw)
    rs = np.random.RandomState(11)
    prompts = [rs.randint(0, 256, (L,)) for L in (3, 9, 17, 50)]
    rids = [eng.submit(p, max_new_tokens=3) for p in prompts]
    eng.run_until_complete(500)
    rids.append(eng.submit(prompts[-1].copy(), max_new_tokens=3))
    eng.run_until_complete(100)
    assert all(eng.result(r).finished for r in rids)
    observed = dict(eng.core.trace_counts)
    observed.update(eng.core.block_pool.trace_counts)
    return eng, observed


def _check_against_plane(plane, observed, leg):
    # every counter the runtime ticked must be a program set the static
    # analysis enumerated — a missing counter IS manifest drift
    for counter, count in sorted(observed.items()):
        if count <= 0:
            continue
        assert counter in plane, (
            f"[{leg}] runtime traced '{counter}' x{count} but the "
            f"manifest has no such program on the {ENGINE_PLANE} plane "
            f"(static analysis missed a compile unit); manifest "
            f"counters: {sorted(plane)}")
    # and the static upper bounds hold: ONE decode / verify / gather /
    # scatter
    for counter in ("decode", "verify", "gather", "scatter"):
        entry = plane[counter]
        assert entry["upper_bound"] == "1", (
            f"[{leg}] manifest bound for '{counter}' is "
            f"{entry['upper_bound']!r}, expected '1' "
            f"(programs: {entry['programs']})")
        assert observed.get(counter, 0) <= 1, (
            f"[{leg}] runtime compiled {observed[counter]} '{counter}' "
            f"programs, exceeding the static bound of 1 for "
            f"{entry['programs']}")
    assert plane["prefill"]["key_space"] == "bucketed", (
        f"[{leg}] prefill key space drifted: {plane['prefill']}")
    assert 0 < observed.get("prefill", 0) <= MAX_PREFILL, (
        f"[{leg}] prefill traced {observed.get('prefill')} times, "
        f"outside (0, {MAX_PREFILL}] for programs "
        f"{plane['prefill']['programs']}")
    # at least one decode step actually ran — a zero here means the leg
    # did not exercise the plane and the subset check proved nothing
    assert observed.get("decode", 0) == 1, (
        f"[{leg}] expected exactly one decode trace, got "
        f"{observed.get('decode')}")


def test_plane_is_the_pinned_program_set(engine_plane):
    """The static side of the pin: the EngineCore plane holds exactly
    the five counters, with ONE-program bounds on
    decode/verify/gather/scatter and a bucketed prefill."""
    assert set(engine_plane) == {"prefill", "decode", "verify",
                                 "gather", "scatter"}, (
        f"plane counters drifted: {sorted(engine_plane)}")
    # both decode VARIANTS (composed + fused) share one holder — the
    # manifest proves at most one compiles per process; same for the
    # verify variants (composed + tp shard_map)
    assert engine_plane["decode"]["holders"] == ["_decode_fn"]
    assert engine_plane["verify"]["holders"] == ["_verify_fn"]
    assert engine_plane["verify"]["upper_bound"] == "1"


def test_leg_tp1_composed(engine_plane):
    eng, observed = _run_leg(fused_decode=False)
    assert eng.core.decode_path == "unfused"
    _check_against_plane(engine_plane, observed, "tp1-composed")
    assert observed["gather"] == 1 and observed["scatter"] == 1


def test_leg_tp1_fused(engine_plane):
    eng, observed = _run_leg(fused_decode=True)
    assert eng.core.decode_path == "fused"
    _check_against_plane(engine_plane, observed, "tp1-fused")


def test_leg_tp2_composed(engine_plane):
    eng, observed = _run_leg(tensor_parallel=2)
    _check_against_plane(engine_plane, observed, "tp2-composed")
    assert observed["gather"] == 1 and observed["scatter"] == 1


def test_leg_tp1_spec(engine_plane):
    """Speculation on (ISSUE 18): a cyclic prompt guarantees the n-gram
    table proposes, so the verify program dispatches — and still traces
    exactly ONCE alongside the one decode (steps where nothing was
    proposed fall back to it)."""
    eng, observed = _run_leg(spec_k=3)
    assert eng.core.spec_on and eng.spec_fallback_reason is None
    r = eng.submit(np.tile([5, 6, 7, 8], 8), max_new_tokens=8)
    eng.run_until_complete(100)
    assert eng.result(r).finished
    observed = dict(eng.core.trace_counts)
    observed.update(eng.core.block_pool.trace_counts)
    _check_against_plane(engine_plane, observed, "tp1-spec")
    assert observed["verify"] == 1, (
        f"expected exactly one verify trace, got {observed.get('verify')}")
    assert eng.metrics.snapshot()["spec_draft_tokens"] > 0
