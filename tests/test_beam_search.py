"""beam_search: exact brute-force oracle on a Markov toy model (whose
next-token logits depend only on the last token, so every path's score is
enumerable), plus GPT integration parity checks."""

import itertools

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from paddle_tpu.models import GPTForCausalLM, gpt_tiny
from paddle_tpu.models.generation import beam_search


class MarkovLM:
    """decode_step returns T[last_token] — beam search over it is exactly
    enumerable.  Carries a real batch-shaped cache leaf so the beam
    tile/gather machinery is exercised."""

    def __init__(self, table):
        self.T = jnp.asarray(table, jnp.float32)

    def init_cache(self, batch, max_len):
        return [jnp.zeros((batch, 1))]

    def decode_step(self, input_ids, caches, position):
        return self.T[input_ids], caches


def _brute_force(table, prompt_last, n, eos=None, lp=0.0):
    """Best continuation by exhaustive enumeration (numpy)."""
    V = table.shape[0]
    logp = table - np.log(np.exp(table).sum(-1, keepdims=True))
    best_seq, best_score = None, -np.inf
    for path in itertools.product(range(V), repeat=n):
        score, prev, length = 0.0, prompt_last, n
        done = False
        valid = True
        for i, tok in enumerate(path):
            if done:
                if tok != (eos if eos is not None else tok):
                    valid = False  # frozen beams only continue with pad
                    break
                continue  # pad after eos: zero cost
            score += logp[prev, tok]
            prev = tok
            if eos is not None and tok == eos:
                done = True
                length = i + 1
        if not valid:
            continue
        final = score / (length ** lp) if lp else score
        if final > best_score:
            best_score, best_seq = final, path
    return list(best_seq), best_score


@pytest.mark.parametrize("eos", [None, 3])
def test_beam_exhaustive_matches_brute_force(eos):
    rs = np.random.RandomState(0)
    V, n = 5, 3
    table = rs.randn(V, V).astype(np.float32) * 2.0
    model = MarkovLM(table)
    prompt = jnp.asarray([[2]])
    # beam_size == V^... : width V**n guarantees exhaustive search
    seq, score = beam_search(model, prompt, max_new_tokens=n,
                             beam_size=V ** n, eos_token_id=eos)
    want_seq, want_score = _brute_force(table, 2, n, eos=eos)
    got = np.asarray(seq)[0, 1:].tolist()
    assert got == want_seq, (got, want_seq)
    np.testing.assert_allclose(float(score[0]), want_score, rtol=1e-5)


def test_beam_length_penalty_changes_winner():
    # eos from token 1 is cheap and immediate; longer paths through
    # token 0 accumulate more raw log-prob — length penalty arbitrates
    rs = np.random.RandomState(1)
    V, n, eos = 4, 3, 3
    table = rs.randn(V, V).astype(np.float32)
    model = MarkovLM(table)
    prompt = jnp.asarray([[0]])
    for lp in (0.0, 2.0):
        seq, score = beam_search(model, prompt, max_new_tokens=n,
                                 beam_size=V ** n, eos_token_id=eos,
                                 length_penalty=lp)
        want_seq, want_score = _brute_force(table, 0, n, eos=eos, lp=lp)
        assert np.asarray(seq)[0, 1:].tolist() == want_seq
        np.testing.assert_allclose(float(score[0]), want_score, rtol=1e-5)


def test_beam_1_equals_greedy_gpt():
    rs = np.random.RandomState(2)
    model = GPTForCausalLM(gpt_tiny())
    ids = jnp.asarray(rs.randint(0, 256, (2, 5)))
    greedy = model.generate(ids, max_new_tokens=5)
    seq, score = beam_search(model, ids, max_new_tokens=5, beam_size=1)
    np.testing.assert_array_equal(np.asarray(seq), np.asarray(greedy))
    assert np.all(np.isfinite(np.asarray(score)))


def test_partial_beam_bounded_by_exhaustive():
    """A pruning beam's best score never EXCEEDS the exhaustive optimum
    (the guaranteed direction — wider is not always better, the
    non-monotonicity of pruned beam search is well known), and at
    exhaustive width it attains it exactly."""
    rs = np.random.RandomState(3)
    V, n = 5, 3
    table = rs.randn(V, V).astype(np.float32)
    model = MarkovLM(table)
    prompt = jnp.asarray([[1]])
    _, exact = beam_search(model, prompt, max_new_tokens=n,
                           beam_size=V ** n)
    for width in (1, 2, 4):
        _, s = beam_search(model, prompt, max_new_tokens=n,
                           beam_size=width)
        assert float(s[0]) <= float(exact[0]) + 1e-5


def test_beam_under_jit():
    model = MarkovLM(np.random.RandomState(4).randn(5, 5))
    prompt = jnp.asarray([[1], [4]])

    @jax.jit
    def run(ids):
        return beam_search(model, ids, max_new_tokens=4, beam_size=3)

    seq, score = run(prompt)
    seq2, score2 = beam_search(model, prompt, max_new_tokens=4, beam_size=3)
    np.testing.assert_array_equal(np.asarray(seq), np.asarray(seq2))
    np.testing.assert_allclose(np.asarray(score), np.asarray(score2),
                               rtol=1e-6)


def test_beam_pad_token_past_vocab():
    """pad ids appended past the base vocab must be EMITTED verbatim by
    frozen beams (the in-vocab scoring slot is an internal detail)."""
    rs = np.random.RandomState(5)
    V, eos, pad = 5, 3, 7            # pad >= vocab
    table = rs.randn(V, V).astype(np.float32)
    table[:, eos] += 3.0             # eos very likely -> beams finish
    model = MarkovLM(table)
    seq, _ = beam_search(model, jnp.asarray([[0]]), max_new_tokens=4,
                         beam_size=2, eos_token_id=eos, pad_token_id=pad)
    row = np.asarray(seq)[0, 1:].tolist()
    assert eos in row
    after = row[row.index(eos) + 1:]
    assert all(t == pad for t in after), row   # pad, not vocab-1
