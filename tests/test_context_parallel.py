"""Context-parallel (sep axis) tests: ring attention and Ulysses all-to-all
attention must equal full single-device attention — the reference's
parallel==serial oracle applied to long-context (SURVEY.md §5)."""

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.distributed.meta_parallel.context_parallel import (
    ring_attention, ulysses_attention, RingAttention)


def full_attention(q, k, v, causal):
    qf = q.astype(jnp.float32)
    s = jnp.einsum("bshd,bthd->bhst", qf, k.astype(jnp.float32))
    s = s / np.sqrt(q.shape[-1])
    if causal:
        S = s.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask[None, None], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhst,bthd->bshd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


def make_qkv(B=2, S=32, H=8, D=16, seed=0):
    r = np.random.RandomState(seed)
    mk = lambda: jnp.asarray(r.randn(B, S, H, D).astype(np.float32) * 0.3)
    return mk(), mk(), mk()


def sep_mesh(n=8):
    return Mesh(np.asarray(jax.devices()[:n]), ("sep",))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    q, k, v = make_qkv()
    ref = full_attention(q, k, v, causal)
    mesh = sep_mesh()
    with mesh:
        q_s = jax.device_put(q, NamedSharding(mesh, P(None, "sep")))
        k_s = jax.device_put(k, NamedSharding(mesh, P(None, "sep")))
        v_s = jax.device_put(v, NamedSharding(mesh, P(None, "sep")))
        out = ring_attention(q_s, k_s, v_s, causal=causal, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_attention_matches_full(causal):
    q, k, v = make_qkv()
    ref = full_attention(q, k, v, causal)
    mesh = sep_mesh()
    with mesh:
        out = ulysses_attention(q, k, v, causal=causal, mesh=mesh)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_under_jit_trains():
    """Grad flows through the ring (ppermute/while differentiable)."""
    q, k, v = make_qkv(S=16)
    mesh = sep_mesh(4)

    def loss(q, k, v):
        out = ring_attention(q, k, v, causal=True, mesh=mesh)
        return jnp.sum(out ** 2)

    with mesh:
        g = jax.jit(jax.grad(loss))(q, k, v)
    assert np.all(np.isfinite(np.asarray(g)))
    # compare grad vs full-attention grad
    g_ref = jax.grad(lambda q: jnp.sum(full_attention(q, k, v, True) ** 2))(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=5e-4, atol=5e-5)


def test_ring_attention_class_wrapper():
    q, k, v = make_qkv(S=16)
    mesh = sep_mesh(4)
    with mesh:
        out = RingAttention()(q, k, v, mesh=mesh)
    ref = full_attention(q, k, v, True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_rejects_indivisible_seq():
    q, k, v = make_qkv(S=30)
    with pytest.raises(ValueError):
        ring_attention(q, k, v, mesh=sep_mesh())


def test_ulysses_with_batch_sharding():
    """Composes with a dp-sharded batch (partial-manual shard_map)."""
    q, k, v = make_qkv(B=4, S=16, H=4)
    mesh = Mesh(np.asarray(jax.devices()).reshape(2, 4), ("dp", "sep"))
    ref = full_attention(q, k, v, True)
    with mesh:
        out = ulysses_attention(q, k, v, causal=True, mesh=mesh,
                                batch_spec="dp")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_gpt_trainer_with_sep_ring_attention():
    """End-to-end: hybrid trainer with sep_degree=4 + ring attention trains
    and matches the sep=1 loss on the same data (parallel==serial oracle)."""
    import paddle_tpu
    import paddle_tpu.distributed as dist
    import paddle_tpu.optimizer as opt
    from paddle_tpu.models import GPTConfig, GPTHybridTrainer

    def run(sep, cp):
        paddle_tpu.seed(11)
        s = dist.DistributedStrategy()
        s.hybrid_configs = {"dp_degree": 2 if sep == 1 else 1,
                            "mp_degree": 1, "pp_degree": 1,
                            "sep_degree": sep}
        dist.fleet.init(is_collective=True, strategy=s,
                        devices=jax.devices()[: (2 if sep == 1 else sep)])
        hcg = dist.get_hybrid_communicate_group()
        cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                        num_heads=4, max_seq_len=32, dropout=0.0,
                        remat=False, cp=cp)
        tr = GPTHybridTrainer(cfg, hcg, opt.AdamW(learning_rate=1e-3))
        st = tr.init_state()
        x, y = tr.make_batch(batch=4, seq=32, seed=0)
        losses = []
        for _ in range(3):
            st, loss = tr.train_step(st, x, y)
            losses.append(float(loss))
        return losses

    base = run(1, None)
    ring = run(4, "ring")
    np.testing.assert_allclose(ring, base, rtol=2e-3)
